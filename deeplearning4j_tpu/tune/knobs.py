"""Typed registry of the framework's performance knobs.

Every knob the tuner may turn is declared here once: its environment
variable, the value domain worth searching, the built-in default, and the
scope it acts in (``fit`` — the training step builder; ``serve`` — the
inference/dispatch path; ``both``). The registry is the single source of
truth shared by the search (`tune.search` enumerates domains from it), the
tuning DB (entries store knob *names*, resolved back through the registry
at apply time), and the docs (docs/TUNING.md renders this table).

Knobs act through environment variables read at step-BUILD time, never
inside a trace — applying one therefore only affects executables compiled
afterwards, which is why `tune.maybe_apply` runs at fit()/serve startup
before anything compiles.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

__all__ = ["Knob", "KNOBS", "get", "all_knobs", "registry_dict"]

_KINDS = ("int", "float", "str")
_SCOPES = ("fit", "serve", "both")


@dataclass(frozen=True)
class Knob:
    """One tunable: ``domain`` is the ordered candidate set the search
    enumerates (declaration order is the deterministic trial order);
    ``default`` must be a member of ``domain`` so the un-tuned baseline is
    always in the race and the winner is ≥ default by construction."""

    name: str
    env: str
    kind: str          # "int" | "float" | "str"
    domain: Tuple[Any, ...]
    default: Any
    scope: str         # "fit" | "serve" | "both"
    help: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"knob {self.name}: bad kind {self.kind!r}")
        if self.scope not in _SCOPES:
            raise ValueError(f"knob {self.name}: bad scope {self.scope!r}")
        if self.default not in self.domain:
            raise ValueError(
                f"knob {self.name}: default {self.default!r} not in domain")

    # -- value plumbing ----------------------------------------------------

    def parse(self, raw: str) -> Any:
        """Env-string → typed value (the inverse of ``format``)."""
        if self.kind == "int":
            return int(raw)
        if self.kind == "float":
            return float(raw)
        return str(raw)

    def format(self, value: Any) -> str:
        """Typed value → the exact string the consuming env reader expects."""
        if self.kind == "int":
            return str(int(value))
        if self.kind == "float":
            return repr(float(value))
        return str(value)

    def validate(self, value: Any) -> Any:
        """Round-trip ``value`` through the env encoding and check domain
        membership. Returns the canonical typed value."""
        v = self.parse(self.format(value))
        if v not in self.domain:
            raise ValueError(
                f"knob {self.name}: {value!r} not in domain {self.domain}")
        return v

    def applies_to(self, scope: str) -> bool:
        return self.scope == "both" or self.scope == scope

    # -- serde (DB + tests round-trip through this) ------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "env": self.env, "kind": self.kind,
            "domain": list(self.domain), "default": self.default,
            "scope": self.scope, "help": self.help,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Knob":
        return Knob(
            name=d["name"], env=d["env"], kind=d["kind"],
            domain=tuple(d["domain"]), default=d["default"],
            scope=d["scope"], help=d.get("help", ""),
        )


def _mesh_axis_domain() -> Tuple[int, ...]:
    """Finite per-axis domain for the mesh-shape knobs: 0 (= auto) plus the
    powers of two up to the local device count. Uses the already-initialized
    jax backend when available; otherwise assumes the 8-device dev mesh
    (tools/bench_smoke.sh, tests/conftest.py) — never imports jax here, and
    never touches a merely-imported jax whose backend hasn't been created
    (device_count() would initialize it), since knob registration must not
    force backend init before the bench harness sets its platform env."""
    import sys

    n = 8
    xb = sys.modules.get("jax._src.xla_bridge")
    if "jax" in sys.modules and xb is not None and getattr(xb, "_backends", None):
        try:
            n = sys.modules["jax"].local_device_count()
        except Exception:
            pass
    dom, p = [0], 1
    while p <= n:
        dom.append(p)
        p *= 2
    return tuple(dom)


KNOBS: Tuple[Knob, ...] = (
    Knob(
        name="bucket_min", env="DL4J_TPU_BUCKET_MIN", kind="int",
        domain=(1, 4, 8), default=1, scope="both",
        help="smallest rung of the geometric bucket ladder",
    ),
    Knob(
        name="bucket_growth", env="DL4J_TPU_BUCKET_GROWTH", kind="float",
        domain=(1.5, 2.0, 4.0), default=2.0, scope="both",
        help="bucket-ladder growth factor (fewer, coarser rungs when large)",
    ),
    Knob(
        name="chain_steps", env="DL4J_TPU_CHAIN_STEPS", kind="str",
        domain=("auto", "0", "4", "8", "16"), default="auto", scope="fit",
        help="chained-dispatch K: steps fused into one device dispatch",
    ),
    Knob(
        name="rnn_unroll", env="DL4J_TPU_RNN_UNROLL", kind="int",
        domain=(1, 4, 8, 16), default=8, scope="both",
        help="lax.scan unroll factor for recurrent layers",
    ),
    Knob(
        name="flash_block_q", env="DL4J_TPU_FLASH_BLOCK_Q", kind="int",
        domain=(64, 128, 256), default=128, scope="both",
        help="flash-attention query block size",
    ),
    Knob(
        name="flash_block_k", env="DL4J_TPU_FLASH_BLOCK_K", kind="int",
        domain=(64, 128, 256), default=128, scope="both",
        help="flash-attention key/value block size",
    ),
    Knob(
        name="compress_threshold", env="DL4J_TPU_COMPRESS_THRESHOLD",
        kind="float", domain=(1e-4, 1e-3, 1e-2), default=1e-3, scope="fit",
        help="gradient-compression residual threshold (DP exchange)",
    ),
    Knob(
        name="grad_accum", env="DL4J_TPU_GRAD_ACCUM", kind="int",
        domain=(1, 2, 4, 8), default=1, scope="fit",
        help="gradient-accumulation micro-batches per optimizer step "
             "(lax.scan inside the donated step; 1/A activation footprint)",
    ),
    Knob(
        name="mesh_data", env="DL4J_TPU_MESH_DATA", kind="int",
        domain=_mesh_axis_domain(), default=0, scope="fit",
        help="mesh data-axis size for the named-mesh step "
             "(parallel/mesh_step.py; 0 = auto: all devices left over after "
             "the model/pipe axes)",
    ),
    Knob(
        name="mesh_model", env="DL4J_TPU_MESH_MODEL", kind="int",
        domain=_mesh_axis_domain(), default=0, scope="fit",
        help="mesh tensor-parallel axis size (Megatron TP rules, "
             "parallel/tp.py; 0 = 1 = off)",
    ),
    Knob(
        name="mesh_pipe", env="DL4J_TPU_MESH_PIPE", kind="int",
        domain=_mesh_axis_domain(), default=0, scope="fit",
        help="mesh stage-axis size: carries the cross-replica sharded "
             "weight update in the unified step (arXiv 2004.13336) and the "
             "gpipe stage compute (0 = 1 = off)",
    ),
    Knob(
        name="kv_page_tokens", env="DL4J_TPU_KV_PAGE_TOKENS", kind="int",
        domain=(16, 32, 64, 128), default=64, scope="serve",
        help="KV-cache page size in tokens (decode engine, nn/decode.py): "
             "small pages waste less cache on short streams, large pages "
             "gather fewer indices per decode step",
    ),
    Knob(
        name="decode_batch_max", env="DL4J_TPU_DECODE_BATCH_MAX", kind="int",
        domain=(4, 8, 16, 32), default=8, scope="serve",
        help="token-level continuous-batching width cap: tokens/s rises "
             "with width until the padded decode step's ITL breaks the "
             "stream SLO",
    ),
    Knob(
        name="ivf_nlist", env="DL4J_TPU_IVF_NLIST", kind="int",
        domain=(0, 64, 128, 256, 512), default=0, scope="serve",
        help="IVF coarse-quantizer cell count (0 = auto ~ sqrt(n), bucket-"
             "rounded): more cells shrink each probed posting list but cost "
             "recall at fixed nprobe; acts at index BUILD time",
    ),
    Knob(
        name="ivf_nprobe", env="DL4J_TPU_IVF_NPROBE", kind="int",
        domain=(4, 8, 16, 32), default=8, scope="serve",
        help="IVF cells scanned per query: the recall/latency dial — "
             "candidates scanned grow linearly with nprobe while recall "
             "saturates; acts at index BUILD time (fixes the warmed grid)",
    ),
    Knob(
        name="search_batch_max", env="DL4J_TPU_SEARCH_BATCH_MAX", kind="int",
        domain=(8, 16, 32, 64), default=32, scope="serve",
        help="query-coalescing width cap for /v1/search: wider batches "
             "amortize kernel launches until the padded top-k step blows "
             "the per-request deadline",
    ),
)

_BY_NAME: Dict[str, Knob] = {k.name: k for k in KNOBS}


def get(name: str) -> Optional[Knob]:
    return _BY_NAME.get(name)


def all_knobs(scope: Optional[str] = None) -> Tuple[Knob, ...]:
    if scope is None:
        return KNOBS
    return tuple(k for k in KNOBS if k.applies_to(scope))


def registry_dict() -> Dict[str, Dict[str, Any]]:
    """Full registry as plain dicts (recorded into every DB entry so a
    reader can interpret knob names without importing this module's exact
    revision)."""
    return {k.name: k.to_dict() for k in KNOBS}
