"""CRC'd, toolchain-fingerprinted JSON tuning database.

One zip file (written with the same ``_atomic_write_zip`` tmp/fsync/replace
discipline as checkpoints) holding a single ``tunedb.json`` entry plus a
``tunedb.json.crc32`` sidecar. Entries are keyed by
``model_signature|backend`` and each records the toolchain fingerprint it
was measured under (``nn.aot.toolchain_fingerprint``): at lookup time an
entry whose fingerprint no longer matches the running toolchain is treated
as STALE and ignored — PERF.md documented hand-set values flipping from
+12% to −12% across a toolchain bump, so a stale winner is worse than no
winner. A corrupt file (CRC mismatch, bad JSON, wrong format version) is
rejected whole, counted, and treated as empty; the DB is a cache, never
state.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Optional

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.tune import knobs as _knobs

__all__ = ["TuningDB", "default_db_path", "DB_FORMAT_VERSION"]

DB_FORMAT_VERSION = 1
_JSON_ENTRY = "tunedb.json"
_CRC_ENTRY = "tunedb.json.crc32"

_rejected = obs.counter(
    "dl4j_tune_db_rejected_total",
    "tuning-DB loads rejected (corrupt file or CRC mismatch)")
_stale = obs.counter(
    "dl4j_tune_db_stale_total",
    "tuning-DB lookups discarded for toolchain-fingerprint mismatch")
_hits = obs.counter(
    "dl4j_tune_db_hits_total", "tuning-DB lookups that returned a winner")


def default_db_path() -> str:
    """``$DL4J_TPU_TUNE_DB`` or ``$DL4J_TPU_HOME/tune/tunedb.zip`` (same
    root convention as the pretrained-model cache)."""
    explicit = os.environ.get("DL4J_TPU_TUNE_DB")
    if explicit:
        return explicit
    root = os.environ.get("DL4J_TPU_HOME") or os.path.join(
        os.path.expanduser("~"), ".deeplearning4j_tpu")
    return os.path.join(root, "tune", "tunedb.zip")


def _entry_key(model_signature: str, backend: str) -> str:
    return f"{model_signature}|{backend}"


class TuningDB:
    def __init__(self, path: Optional[str] = None):
        self.path = os.fspath(path) if path is not None else default_db_path()

    # -- load / save -------------------------------------------------------

    def load(self) -> Dict[str, Any]:
        """Read and CRC-verify the DB. Any defect rejects the whole file
        (counted + event) and yields an empty DB — a tuner cache must never
        take the process down."""
        import zipfile

        if not os.path.exists(self.path):
            return {}
        try:
            with zipfile.ZipFile(self.path, "r") as zf:
                raw = zf.read(_JSON_ENTRY)
                want = int(zf.read(_CRC_ENTRY).decode("ascii").strip())
            got = zlib.crc32(raw) & 0xFFFFFFFF
            if got != want:
                raise ValueError(f"CRC mismatch: {got} != {want}")
            doc = json.loads(raw.decode("utf-8"))
            if doc.get("format_version") != DB_FORMAT_VERSION:
                raise ValueError(
                    f"format_version {doc.get('format_version')!r}")
            entries = doc.get("entries")
            if not isinstance(entries, dict):
                raise ValueError("entries missing")
            return entries
        except Exception as e:  # corrupt zip, bad json, crc, version...
            _rejected.inc()
            obs.event("tune_db_rejected", path=self.path, reason=str(e)[:200])
            return {}

    def save(self, entries: Dict[str, Any]) -> None:
        from deeplearning4j_tpu.utils import serialization

        doc = {
            "format_version": DB_FORMAT_VERSION,
            "registry": _knobs.registry_dict(),
            "entries": entries,
        }
        raw = json.dumps(doc, sort_keys=True, indent=1).encode("utf-8")
        crc = str(zlib.crc32(raw) & 0xFFFFFFFF).encode("ascii")

        def write_entries(zf):
            zf.writestr(_JSON_ENTRY, raw)
            zf.writestr(_CRC_ENTRY, crc)

        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        serialization._atomic_write_zip(self.path, write_entries)

    # -- record / lookup ---------------------------------------------------

    def record(self, model_signature: str, winner: Dict[str, Any],
               objective: Dict[str, Any], trials: int,
               toolchain: Optional[Dict[str, str]] = None,
               scope: str = "fit") -> Dict[str, Any]:
        """Persist the winning knob assignment for (signature, backend).
        ``winner`` maps knob *names* to typed values; unknown names are
        rejected so a DB can always be replayed through the registry."""
        from deeplearning4j_tpu.nn import aot

        tc = toolchain or aot.toolchain_fingerprint()
        for name, value in winner.items():
            knob = _knobs.get(name)
            if knob is None:
                raise KeyError(f"unknown knob {name!r}")
            knob.validate(value)
        entry = {
            "model_signature": model_signature,
            "backend": tc["backend"],
            "toolchain": tc,
            "scope": scope,
            "knobs": dict(winner),
            "objective": dict(objective),
            "trials": int(trials),
        }
        entries = self.load()
        entries[_entry_key(model_signature, tc["backend"])] = entry
        self.save(entries)
        obs.event("tune_db_recorded", signature=model_signature[:12],
                  backend=tc["backend"], trials=trials,
                  knobs=json.dumps(winner, sort_keys=True))
        return entry

    def lookup(self, model_signature: str,
               toolchain: Optional[Dict[str, str]] = None,
               allow_stale: bool = False) -> Optional[Dict[str, Any]]:
        """Winner for (signature, current backend), or None. Re-validates
        the recorded toolchain fingerprint on every lookup — a match made
        under jax X on backend Y says nothing about jax X' or backend Y'."""
        from deeplearning4j_tpu.nn import aot

        tc = toolchain or aot.toolchain_fingerprint()
        entry = self.load().get(_entry_key(model_signature, tc["backend"]))
        if entry is None:
            return None
        if entry.get("toolchain") != tc and not allow_stale:
            _stale.inc()
            obs.event("tune_db_stale", signature=model_signature[:12],
                      recorded=json.dumps(entry.get("toolchain"),
                                          sort_keys=True),
                      running=json.dumps(tc, sort_keys=True))
            return None
        _hits.inc()
        return entry
