"""Batched brute-force k-nearest-neighbor search.

The TPU-native replacement for the reference's pointer-chasing search trees
(VPTree.java:48 'search', KDTree.java 'knn'): one fused
distance-matrix + top_k per corpus chunk — a single MXU matmul for the
dominant term — with a streaming top-k merge across chunks so the corpus
never has to fit in one buffer.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_METRICS = ("euclidean", "sqeuclidean", "cosinesimilarity", "cosinedistance",
            "dot", "manhattan")


def pairwise_distance(x, y, metric: str = "euclidean") -> jax.Array:
    """[Q,D] x [N,D] -> [Q,N] distance (or similarity, for *similarity
    metrics) matrix. Euclidean/cosine/dot reduce to one matmul on the MXU."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m = metric.lower()
    if m not in _METRICS:
        raise ValueError(f"unknown metric {metric!r}; one of {_METRICS}")
    if m in ("euclidean", "sqeuclidean"):
        # ||x-y||^2 = ||x||^2 - 2<x,y> + ||y||^2 : the cross term is the matmul
        sq = (
            jnp.sum(x * x, axis=-1, keepdims=True)
            - 2.0 * x @ y.T
            + jnp.sum(y * y, axis=-1)[None, :]
        )
        sq = jnp.maximum(sq, 0.0)
        return sq if m == "sqeuclidean" else jnp.sqrt(sq)
    if m in ("cosinesimilarity", "cosinedistance"):
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        yn = y / jnp.maximum(jnp.linalg.norm(y, axis=-1, keepdims=True), 1e-12)
        sim = xn @ yn.T
        return sim if m == "cosinesimilarity" else 1.0 - sim
    if m == "dot":
        return x @ y.T
    # manhattan: no matmul form; broadcast-reduce (fused by XLA)
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def _larger_is_better(metric: str) -> bool:
    return metric.lower() in ("cosinesimilarity", "dot")


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _chunk_topk(queries, chunk, k: int, metric: str, offset):
    d = pairwise_distance(queries, chunk, metric)
    scores = d if _larger_is_better(metric) else -d
    best, idx = jax.lax.top_k(scores, k)  # [Q,k]
    return best, idx + offset


@functools.partial(jax.jit, static_argnames=("k",))
def _merge_topk(best_a, idx_a, best_b, idx_b, k: int):
    best = jnp.concatenate([best_a, best_b], axis=1)
    idx = jnp.concatenate([idx_a, idx_b], axis=1)
    nb, ni = jax.lax.top_k(best, k)
    return nb, jnp.take_along_axis(idx, ni, axis=1)


def knn_search(
    corpus,
    queries,
    k: int,
    metric: str = "euclidean",
    chunk_size: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact top-k over ``corpus`` for each query row.

    Returns (indices [Q,k], distances [Q,k]) ordered best-first. ``chunk_size``
    bounds the corpus rows scored per step (HBM streaming); each chunk is one
    jitted matmul+top_k, merged into a running top-k.
    """
    corpus = np.asarray(corpus, np.float32)
    queries = np.atleast_2d(np.asarray(queries, np.float32))
    n = corpus.shape[0]
    k = min(k, n)
    if chunk_size is None or chunk_size >= n:
        best, idx = _chunk_topk(jnp.asarray(queries), jnp.asarray(corpus), k, metric, 0)
    else:
        best = idx = None
        for s in range(0, n, chunk_size):
            chunk = corpus[s : s + chunk_size]
            kk = min(k, chunk.shape[0])
            b, i = _chunk_topk(jnp.asarray(queries), jnp.asarray(chunk), kk, metric, s)
            if best is None:
                best, idx = b, i
                if kk < k:  # first chunk smaller than k: widen via merge later
                    pass
            else:
                best, idx = _merge_topk(best, idx, b, i, min(k, best.shape[1] + b.shape[1]))
    dist = np.asarray(best)
    if _larger_is_better(metric):
        pass  # scores ARE the similarity
    else:
        dist = -dist
    return np.asarray(idx), dist
