"""SpTree / QuadTree — Barnes-Hut space-partitioning trees (host-side).

Capability parity with the reference's clustering/sptree/SpTree.java:35 and
clustering/quadtree/QuadTree.java (the support structures behind
plot/BarnesHutTsne.java). Semantics follow the reference exactly:

- nodes summarise their subtree by center-of-mass + cumulative size;
- ``compute_non_edge_forces(i, theta)`` walks the tree and treats a cell as
  a summary when ``max_width / sqrt(D) < theta`` (SpTree.java:210-237),
  accumulating the Student-t repulsive force and the Q normaliser;
- ``compute_edge_forces(row_p, col_p, val_p)`` accumulates the attractive
  force over the sparse P matrix in CSR form (SpTree.java:252-271).

These are pointer trees, so they live on the host (numpy): the point of
Barnes-Hut is to prune work, which is a CPU win and an MXU loss. The
TPU-first t-SNE (`clustering/tsne.py`) therefore keeps the exact fused-jit
gradient as its default, and `BarnesHutTsne(method="barnes_hut")` runs this
tree when the O(n^2) dense form genuinely cannot fit.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class Cell:
    """Axis-aligned cell: corner (center) + half-width per dimension
    (reference sptree/Cell.java)."""

    __slots__ = ("corner", "width")

    def __init__(self, corner: np.ndarray, width: np.ndarray):
        self.corner = np.asarray(corner, np.float64)
        self.width = np.asarray(width, np.float64)

    def contains_point(self, point: np.ndarray) -> bool:
        return bool(np.all(np.abs(self.corner - point) <= self.width + 1e-12))


class SpTree:
    """n-dimensional Barnes-Hut tree over a fixed [n, d] data matrix.

    Construction inserts every row; each node keeps at most one point
    (QT_NODE_CAPACITY=1, duplicates stack on the same leaf like the
    reference's duplicate check, SpTree.java insert path).
    """

    def __init__(self, data, corner: Optional[np.ndarray] = None,
                 width: Optional[np.ndarray] = None, _root: bool = True):
        data = np.asarray(data, np.float64)
        self.data = data
        self.d = data.shape[1]
        self.n_children = 2 ** self.d
        if _root:
            mean = data.mean(axis=0)
            half = np.maximum(
                data.max(axis=0) - mean, mean - data.min(axis=0)) + 1e-5
            corner, width = mean, half
        self.boundary = Cell(corner, width)
        self.center_of_mass = np.zeros(self.d)
        self.cum_size = 0
        self.size = 0
        self.index: List[int] = []
        self.children: List[Optional["SpTree"]] = [None] * self.n_children
        self._is_leaf = True
        if _root:
            for i in range(data.shape[0]):
                self.insert(i)

    # -- construction -----------------------------------------------------

    def is_leaf(self) -> bool:
        return self._is_leaf

    def insert(self, i: int) -> bool:
        point = self.data[i]
        if not self.boundary.contains_point(point):
            return False
        # online center-of-mass update
        self.cum_size += 1
        mult1 = (self.cum_size - 1) / self.cum_size
        self.center_of_mass = self.center_of_mass * mult1 + point / self.cum_size
        if self._is_leaf and self.size == 0:
            self.index.append(i)
            self.size = 1
            return True
        if self._is_leaf:
            # duplicate point: stack on this leaf (reference duplicate check).
            # Near-duplicates also stack once the cell is already tiny —
            # subdividing below the contains_point tolerance would recurse
            # forever (points closer than ~1e-12 but not bit-identical).
            if (np.all(self.data[self.index[0]] == point)
                    or self.boundary.width.max() < 1e-10):
                self.index.append(i)
                self.size += 1
                return True
            self.subdivide()
        for child in self.children:
            if child.insert(i):
                return True
        raise AssertionError("point fell through all children")  # pragma: no cover

    def subdivide(self) -> None:
        """Split into 2^d children and push the stored point(s) down
        (SpTree.java:168-208)."""
        half = self.boundary.width / 2.0
        for c in range(self.n_children):
            offs = np.array([(1 if (c >> bit) & 1 else -1)
                             for bit in range(self.d)], np.float64)
            corner = self.boundary.corner + offs * half
            self.children[c] = SpTree(self.data, corner, half, _root=False)
        self._is_leaf = False
        old, self.size = self.index, 0
        self.index = []
        for i in old:
            for child in self.children:
                if child.insert(i):
                    break

    def depth(self) -> int:
        if self._is_leaf:
            return 1
        return 1 + max(c.depth() for c in self.children if c is not None)

    # -- Barnes-Hut forces -------------------------------------------------

    def compute_non_edge_forces(self, point_index: int, theta: float,
                                ) -> Tuple[np.ndarray, float]:
        """Repulsive force on one point: returns (negative_force [d], sum_q).
        Iterative traversal of the reference's recursion (SpTree.java:210)."""
        point = self.data[point_index]
        neg = np.zeros(self.d)
        sum_q = 0.0
        stack = [self]
        while stack:
            node = stack.pop()
            if node.cum_size == 0 or (
                    node._is_leaf and node.size == 1
                    and node.index[0] == point_index):
                continue
            buf = point - node.center_of_mass
            dist2 = float(buf @ buf)
            max_width = float(node.boundary.width.max())
            if node._is_leaf or max_width / max(np.sqrt(dist2), 1e-12) < theta:
                # self-interaction inside a stacked-duplicate leaf: the
                # reference includes it; so do we (exact only for size==1)
                q = 1.0 / (1.0 + dist2)
                mult = node.cum_size * q
                sum_q += mult
                neg += buf * (mult * q)
            else:
                stack.extend(c for c in node.children if c is not None)
        return neg, sum_q

    def compute_edge_forces(self, row_p, col_p, val_p) -> np.ndarray:
        """Attractive forces over sparse P (CSR): returns pos_f [n, d]
        (SpTree.java:252-271) — vectorized over all edges at once."""
        row_p = np.asarray(row_p, np.int64)
        col_p = np.asarray(col_p, np.int64)
        val_p = np.asarray(val_p, np.float64)
        n = row_p.size - 1
        counts = np.diff(row_p)
        src = np.repeat(np.arange(n), counts)
        diff = self.data[src] - self.data[col_p]            # [nnz, d]
        # Student-t attraction p_ij/(1+d2) — the reference divides by
        # (1e-12 + d2) (SpTree.java:262-263), a deviation from the BH-tSNE
        # paper/implementation it is based on; we keep the correct kernel
        d2 = 1.0 + np.sum(diff * diff, axis=1)
        w = (val_p / d2)[:, None] * diff
        pos_f = np.zeros((n, self.d))
        np.add.at(pos_f, src, w)
        return pos_f


class QuadTree(SpTree):
    """2-D specialisation (reference clustering/quadtree/QuadTree.java).
    The reference hard-codes QT_NO_DIMS=2; this class asserts it and exposes
    the compass-named children."""

    def __init__(self, data):
        data = np.asarray(data, np.float64)
        if data.shape[1] != 2:
            raise ValueError(f"QuadTree is 2-D only, got d={data.shape[1]}")
        super().__init__(data)

    def _compass(self, idx: int) -> Optional[SpTree]:
        return self.children[idx] if not self._is_leaf else None

    @property
    def north_west(self):  # (-x, +y)
        return self._compass(0b10)

    @property
    def north_east(self):  # (+x, +y)
        return self._compass(0b11)

    @property
    def south_west(self):  # (-x, -y)
        return self._compass(0b00)

    @property
    def south_east(self):  # (+x, -y)
        return self._compass(0b01)


def barnes_hut_gradient(y: np.ndarray, row_p, col_p, val_p,
                        theta: float = 0.5) -> np.ndarray:
    """One t-SNE gradient via Barnes-Hut: 4*(attr - rep/sum_q), the exact
    combination BarnesHutTsne.java computes from the two force passes."""
    y = np.asarray(y, np.float64)
    tree = SpTree(y)
    pos_f = tree.compute_edge_forces(row_p, col_p, val_p)
    neg_f = np.zeros_like(y)
    sum_q = 0.0
    for i in range(y.shape[0]):
        f, q = tree.compute_non_edge_forces(i, theta)
        neg_f[i] = f
        sum_q += q
    return 4.0 * (pos_f - neg_f / max(sum_q, 1e-12))
