"""VPTree / KDTree — exact-search APIs over the batched brute-force kernel.

Capability parity with clustering/vptree/VPTree.java:48 and
clustering/kdtree/KDTree.java. The reference builds pointer-chasing trees to
prune distance evaluations on CPU; on TPU the un-pruned batched scan
(knn.knn_search: matmul + top_k per chunk) is faster at reference scale and
exactly as exact, so these classes keep the reference's construction/search
surface but delegate to that kernel.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.clustering.knn import knn_search, pairwise_distance


class VPTree:
    """``VPTree(items, similarity_function='euclidean', invert=False)``;
    ``search(target, k)`` -> (items, distances) best-first (reference
    VPTree.search). ``invert=True`` flips the ordering objective, like the
    reference's use for similarity functions."""

    EUCLIDEAN = "euclidean"

    def __init__(self, items, similarity_function: str = "euclidean",
                 invert: bool = False, workers: int = 1, chunk_size: int = 65536):
        self.items = np.asarray(items, np.float32)
        self.similarity_function = similarity_function
        self.invert = bool(invert)
        self.workers = workers  # kept for API parity; search is one device op
        self.chunk_size = chunk_size

    def search(self, target, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """k nearest items to ``target``: (items [k,D], distances [k])."""
        metric = self.similarity_function
        if self.invert:
            # inverted objective: farthest-first under the metric
            d = np.asarray(
                pairwise_distance(np.atleast_2d(np.asarray(target, np.float32)),
                                  self.items, metric)
            )[0]
            order = np.argsort(-d)[: min(k, d.size)]
            return self.items[order], d[order]
        idx, dist = knn_search(self.items, np.atleast_2d(target), k,
                               metric=metric, chunk_size=self.chunk_size)
        return self.items[idx[0]], dist[0]

    def get_items(self) -> np.ndarray:
        return self.items

    def distance(self, a, b) -> float:
        return float(
            pairwise_distance(np.atleast_2d(a), np.atleast_2d(b),
                              self.similarity_function)[0, 0]
        )


class KDTree:
    """``KDTree(dims)`` with ``insert(point)``, ``nn(point)``,
    ``knn(point, distance)`` (reference kdtree/KDTree.java: knn returns all
    points within ``distance``, nearest first; nn returns (distance, point)).
    Mutable corpus; each search is the exact batched scan."""

    def __init__(self, dims: int):
        self.dims = int(dims)
        self._points: List[np.ndarray] = []

    def insert(self, point) -> None:
        p = np.asarray(point, np.float32).reshape(-1)
        if p.shape[0] != self.dims:
            raise ValueError(f"expected dim {self.dims}, got {p.shape[0]}")
        self._points.append(p)

    def delete(self, point) -> bool:
        p = np.asarray(point, np.float32).reshape(-1)
        for i, q in enumerate(self._points):
            if np.array_equal(p, q):
                del self._points[i]
                return True
        return False

    def size(self) -> int:
        return len(self._points)

    def _corpus(self) -> np.ndarray:
        if not self._points:
            raise RuntimeError("empty KDTree")
        return np.stack(self._points)

    def nn(self, point) -> Tuple[float, np.ndarray]:
        idx, dist = knn_search(self._corpus(), np.atleast_2d(point), 1)
        return float(dist[0, 0]), self._corpus()[idx[0, 0]]

    def knn(self, point, distance: float) -> List[Tuple[float, np.ndarray]]:
        corpus = self._corpus()
        d = np.asarray(
            pairwise_distance(np.atleast_2d(np.asarray(point, np.float32)),
                              corpus, "euclidean")
        )[0]
        order = np.argsort(d)
        return [(float(d[i]), corpus[i]) for i in order if d[i] <= distance]
