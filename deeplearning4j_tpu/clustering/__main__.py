"""Nearest-neighbors server CLI: ``python -m deeplearning4j_tpu.clustering``.

Reference parity: deeplearning4j-nearestneighbors-parent/nearestneighbor-server
NearestNeighborsServer.java (flag-driven standalone HTTP kNN server).

Example::

    python -m deeplearning4j_tpu.clustering --points vectors.npy --port 9000 \
        --similarity euclidean
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.clustering",
        description="Serve k-nearest-neighbors queries over a point set.")
    p.add_argument("--points", required=True,
                   help=".npy [N,D] array or .npz with array 'points'")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--similarity", default="euclidean",
                   choices=["euclidean", "cosine", "manhattan", "dot"])
    p.add_argument("--invert", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu.clustering.server import NearestNeighborsServer

    if args.points.endswith(".npz"):
        d = np.load(args.points)
        pts = d["points"] if "points" in d else d[d.files[0]]
    else:
        pts = np.load(args.points)
    srv = NearestNeighborsServer(pts, similarity_function=args.similarity,
                                 invert=args.invert).start(args.port)
    print(f"nearest-neighbors server on port {srv.port} "
          f"({pts.shape[0]} points, dim {pts.shape[1]})", flush=True)
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
