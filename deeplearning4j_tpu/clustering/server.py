"""Nearest-neighbors HTTP server — DEPRECATED shim over the unified stack.

The standalone ThreadingHTTPServer this module used to carry is retired:
the ``/knn`` / ``/knnnew`` / ``/status`` wire contract now lives on the
unified inference server (``serve/server.py``), so there is ONE HTTP
stack, one SLO tracker and one ``/metrics`` endpoint for predict,
generate and search alike. :class:`NearestNeighborsServer` survives as a
thin compatibility shim: same constructor, same ``start(port)`` /
``stop()`` / ``.port`` surface, same JSON responses — but ``start`` now
builds an exact-tier :class:`~deeplearning4j_tpu.search.index.VectorIndex`
and serves it through :class:`~deeplearning4j_tpu.serve.InferenceServer`.
Prefer ``serve.ModelRegistry().register_index(...)`` +
``POST /v1/search`` for new code (docs/SEARCH.md).

Metrics the device index does not speak (sqeuclidean / manhattan / dot /
inverted similarity) fall back to the legacy in-module server so the old
CLI keeps answering; that path warns and will be removed with the shim.

POST /knn     {"ndarray": <row index>, "k": 5}
POST /knnnew  {"ndarray": [..vector..], "k": 5}
Response      {"results": [{"index": i, "distance": d}, ...]}
GET  /status  {"ok": true, "points": N, "dim": D}
"""

from __future__ import annotations

import json
import threading
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.knn import knn_search

# legacy similarity name -> VectorIndex metric; anything absent here (or
# invert=True) cannot be expressed by the device index and stays legacy
_UNIFIED_METRICS = {
    "euclidean": "euclidean",
    "cosine": "cosine",
    "cosinedistance": "cosine",
}


class NearestNeighborsServer:
    """``NearestNeighborsServer(points, similarity_function).start(port)``;
    ``stop()`` to shut down. Port 0 picks a free port (see ``.port``).

    Deprecated: a compatibility front for the unified serving stack — see
    the module docstring."""

    def __init__(self, points, similarity_function: str = "euclidean",
                 invert: bool = False):
        warnings.warn(
            "clustering.server.NearestNeighborsServer is deprecated: the "
            "/knn routes now live on the unified inference server — use "
            "serve.ModelRegistry().register_index(...) and POST /v1/search "
            "(docs/SEARCH.md)", DeprecationWarning, stacklevel=2)
        self.points = np.asarray(points, np.float32)
        self.similarity_function = similarity_function
        self.invert = invert
        self._srv = None          # unified InferenceServer
        self._legacy: Optional[_LegacyNearestNeighborsServer] = None
        self.port: Optional[int] = None

    def start(self, port: int = 9000) -> "NearestNeighborsServer":
        metric = _UNIFIED_METRICS.get(self.similarity_function.lower())
        if metric is None or self.invert:
            warnings.warn(
                f"similarity {self.similarity_function!r} (invert="
                f"{self.invert}) is not served by the device index; "
                "falling back to the legacy brute-force server",
                DeprecationWarning, stacklevel=2)
            self._legacy = _LegacyNearestNeighborsServer(
                self.points, self.similarity_function, self.invert
            ).start(port)
            self.port = self._legacy.port
            return self
        from deeplearning4j_tpu.search import IndexConfig, VectorIndex
        from deeplearning4j_tpu.serve import InferenceServer, ModelRegistry

        index = VectorIndex.build(self.points, IndexConfig(
            dim=int(self.points.shape[1]), name="default", metric=metric,
            ivf=False, pending_cap=0, max_k=64))
        registry = ModelRegistry()
        # compat shim favors startup latency over first-request latency:
        # the exact tier lazy-compiles one executable per reached bucket
        registry.register_index("default", index, warm=False)
        self._srv = InferenceServer(registry).start(port=port)
        self.port = self._srv.port
        return self

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.stop()
            self._srv = None
        if self._legacy is not None:
            self._legacy.stop()
            self._legacy = None


class _LegacyNearestNeighborsServer:
    """The pre-unification stdlib server, kept verbatim for the metric
    combinations the device index does not express. Scheduled for removal
    with the shim."""

    def __init__(self, points, similarity_function: str = "euclidean",
                 invert: bool = False):
        self.points = np.asarray(points, np.float32)
        self.similarity_function = similarity_function
        self.invert = invert
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def _search(self, vec: np.ndarray, k: int):
        idx, dist = knn_search(self.points, vec[None, :], k,
                               metric=self.similarity_function)
        return [
            {"index": int(i), "distance": float(d)}
            for i, d in zip(idx[0], dist[0])
        ]

    def start(self, port: int = 9000) -> "_LegacyNearestNeighborsServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silent: tests spin servers up/down
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/status":
                    self._reply(200, {"ok": True,
                                      "points": int(outer.points.shape[0]),
                                      "dim": int(outer.points.shape[1])})
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    k = int(req.get("k", 1))
                    if self.path == "/knn":
                        row = int(req["ndarray"])
                        vec = outer.points[row]
                        results = outer._search(vec, k + 1)
                        # drop the query row itself (reference /knn semantics).
                        # The [:k] slice only trims when the self row wasn't
                        # among the k+1 hits (duplicate points); when the
                        # corpus caps the search (k >= num points) the
                        # filtered list is already <= k, so no available
                        # neighbor is ever dropped.
                        results = [r for r in results if r["index"] != row][:k]
                    elif self.path == "/knnnew":
                        vec = np.asarray(req["ndarray"], np.float32).reshape(-1)
                        results = outer._search(vec, k)
                    else:
                        self._reply(404, {"error": "unknown path"})
                        return
                    self._reply(200, {"results": results})
                except Exception as e:  # bad request payloads
                    self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread:
                self._thread.join(timeout=10)
                self._thread = None
