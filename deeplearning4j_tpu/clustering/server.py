"""Nearest-neighbors HTTP server.

Capability parity with the reference's nearestneighbor-server
(NearestNeighborsServer: POST /knn for an already-indexed row, POST /knnnew
for a raw vector; JSON request/response DTOs). Stdlib ThreadingHTTPServer —
no framework dependency; the search itself is the jitted batched top-k
(clustering/knn.py), so concurrent requests share one compiled kernel.

POST /knn     {"ndarray": <row index>, "k": 5}
POST /knnnew  {"ndarray": [..vector..], "k": 5}
Response      {"results": [{"index": i, "distance": d}, ...]}
GET  /status  {"ok": true, "points": N, "dim": D}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.knn import knn_search


class NearestNeighborsServer:
    """``NearestNeighborsServer(points, similarity_function).start(port)``;
    ``stop()`` to shut down. Port 0 picks a free port (see ``.port``)."""

    def __init__(self, points, similarity_function: str = "euclidean",
                 invert: bool = False):
        self.points = np.asarray(points, np.float32)
        self.similarity_function = similarity_function
        self.invert = invert
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def _search(self, vec: np.ndarray, k: int):
        idx, dist = knn_search(self.points, vec[None, :], k,
                               metric=self.similarity_function)
        return [
            {"index": int(i), "distance": float(d)}
            for i, d in zip(idx[0], dist[0])
        ]

    def start(self, port: int = 9000) -> "NearestNeighborsServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silent: tests spin servers up/down
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/status":
                    self._reply(200, {"ok": True,
                                      "points": int(outer.points.shape[0]),
                                      "dim": int(outer.points.shape[1])})
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    k = int(req.get("k", 1))
                    if self.path == "/knn":
                        row = int(req["ndarray"])
                        vec = outer.points[row]
                        results = outer._search(vec, k + 1)
                        # drop the query row itself (reference /knn semantics).
                        # The [:k] slice only trims when the self row wasn't
                        # among the k+1 hits (duplicate points); when the
                        # corpus caps the search (k >= num points) the
                        # filtered list is already <= k, so no available
                        # neighbor is ever dropped.
                        results = [r for r in results if r["index"] != row][:k]
                    elif self.path == "/knnnew":
                        vec = np.asarray(req["ndarray"], np.float32).reshape(-1)
                        results = outer._search(vec, k)
                    else:
                        self._reply(404, {"error": "unknown path"})
                        return
                    self._reply(200, {"results": results})
                except Exception as e:  # bad request payloads
                    self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread:
                self._thread.join(timeout=10)
                self._thread = None
