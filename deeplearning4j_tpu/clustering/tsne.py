"""t-SNE embedding (exact, fully jitted).

Capability parity with the reference's plot/BarnesHutTsne.java:65 and
plot/Tsne.java (perplexity-calibrated input similarities, early
exaggeration, momentum gradient descent). TPU-first: Barnes-Hut's quadtree
exists to cut the O(n^2) repulsion on CPU; at the reference's scale the
dense n^2 term is a pair of matmul-shaped reductions the MXU eats whole, so
the exact gradient is both simpler and faster here. ``theta`` is accepted
for API parity and ignored (always exact).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.knn import pairwise_distance


@functools.partial(jax.jit, static_argnames=("max_iter",))
def _binary_search_perplexity(sqd, perplexity, max_iter: int = 50):
    """Per-row beta (precision) so each conditional distribution hits the
    target perplexity; standard bisection, vectorized over rows."""
    n = sqd.shape[0]
    log_u = jnp.log(perplexity)
    eye = jnp.eye(n, dtype=bool)

    def entropy_p(beta):
        p = jnp.exp(-sqd * beta[:, None])
        p = jnp.where(eye, 0.0, p)
        sum_p = jnp.maximum(jnp.sum(p, axis=1), 1e-12)
        h = jnp.log(sum_p) + beta * jnp.sum(sqd * p, axis=1) / sum_p
        return h, p / sum_p[:, None]

    def body(_, carry):
        beta, lo, hi = carry
        h, _ = entropy_p(beta)
        too_high = h > log_u            # entropy too high -> increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(
            jnp.isinf(hi), beta * 2.0,
            jnp.where(jnp.isneginf(lo), beta / 2.0, (lo + hi) / 2.0),
        )
        return beta, lo, hi

    beta0 = jnp.ones(n, sqd.dtype)
    lo0 = jnp.full(n, -jnp.inf, sqd.dtype)
    hi0 = jnp.full(n, jnp.inf, sqd.dtype)
    beta, _, _ = jax.lax.fori_loop(0, max_iter, body, (beta0, lo0, hi0))
    _, p = entropy_p(beta)
    return p


@functools.partial(jax.jit, static_argnames=("n_iter", "stop_lying_iter"))
def _tsne_optimize(p, y0, learning_rate, momentum_init, momentum_final,
                   n_iter: int, stop_lying_iter: int):
    n = p.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def grad_kl(y, pmat):
        sqd = (
            jnp.sum(y * y, axis=1, keepdims=True)
            - 2.0 * y @ y.T
            + jnp.sum(y * y, axis=1)[None, :]
        )
        num = 1.0 / (1.0 + sqd)                    # student-t kernel
        num = jnp.where(eye, 0.0, num)
        q = jnp.maximum(num / jnp.sum(num), 1e-12)
        pq = (pmat - q) * num                      # [n,n]
        # dY_i = 4 * sum_j pq_ij (y_i - y_j) == 4*(diag(row_sums) - pq) @ y
        return 4.0 * ((jnp.sum(pq, axis=1)[:, None] * y) - pq @ y)

    def body(i, carry):
        y, vel, gains = carry
        lying = i < stop_lying_iter
        pmat = jnp.where(lying, p * 4.0, p)
        g = grad_kl(y, pmat)
        momentum = jnp.where(i < 20, momentum_init, momentum_final)
        same_sign = (g > 0) == (vel > 0)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
        )
        vel = momentum * vel - learning_rate * gains * g
        y = y + vel
        y = y - jnp.mean(y, axis=0, keepdims=True)
        return y, vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0))
    )
    return y


class Tsne:
    """Exact t-SNE (reference plot/Tsne.java surface): ``fit_transform(X)``
    returns the [n, n_components] embedding."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 1000,
                 stop_lying_iteration: int = 250, momentum: float = 0.5,
                 final_momentum: float = 0.8, seed: int = 12345):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.stop_lying_iteration = stop_lying_iteration
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.seed = seed
        self.embedding_: Optional[np.ndarray] = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        perp = min(self.perplexity, max((n - 1) / 3.0, 2.0))
        sqd = jnp.asarray(
            pairwise_distance(x, x, "sqeuclidean")
        )
        p_cond = _binary_search_perplexity(sqd, jnp.float32(perp))
        p = (p_cond + p_cond.T) / (2.0 * n)
        p = jnp.maximum(p, 1e-12)
        rs = np.random.RandomState(self.seed)
        y0 = jnp.asarray(rs.randn(n, self.n_components).astype(np.float32) * 1e-2)
        y = _tsne_optimize(
            p, y0, jnp.float32(self.learning_rate), jnp.float32(self.momentum),
            jnp.float32(self.final_momentum), self.n_iter, self.stop_lying_iteration,
        )
        self.embedding_ = np.asarray(y)
        return self.embedding_


class BarnesHutTsne(Tsne):
    """Reference BarnesHutTsne.java:65 API shim: accepts ``theta`` but always
    computes the exact gradient (see module docstring)."""

    def __init__(self, theta: float = 0.5, **kw):
        super().__init__(**kw)
        self.theta = theta

    def fit(self, x) -> "BarnesHutTsne":
        self.fit_transform(x)
        return self
