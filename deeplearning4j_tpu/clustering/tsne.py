"""t-SNE embedding (exact, fully jitted).

Capability parity with the reference's plot/BarnesHutTsne.java:65 and
plot/Tsne.java (perplexity-calibrated input similarities, early
exaggeration, momentum gradient descent). TPU-first: Barnes-Hut's quadtree
exists to cut the O(n^2) repulsion on CPU; at the reference's scale the
dense n^2 term is a pair of matmul-shaped reductions the MXU eats whole, so
the exact gradient is both simpler and faster here. ``theta`` is accepted
for API parity and ignored (always exact).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.knn import pairwise_distance


@functools.partial(jax.jit, static_argnames=("max_iter",))
def _binary_search_perplexity(sqd, perplexity, max_iter: int = 50):
    """Per-row beta (precision) so each conditional distribution hits the
    target perplexity; standard bisection, vectorized over rows."""
    n = sqd.shape[0]
    log_u = jnp.log(perplexity)
    eye = jnp.eye(n, dtype=bool)

    def entropy_p(beta):
        p = jnp.exp(-sqd * beta[:, None])
        p = jnp.where(eye, 0.0, p)
        sum_p = jnp.maximum(jnp.sum(p, axis=1), 1e-12)
        h = jnp.log(sum_p) + beta * jnp.sum(sqd * p, axis=1) / sum_p
        return h, p / sum_p[:, None]

    def body(_, carry):
        beta, lo, hi = carry
        h, _ = entropy_p(beta)
        too_high = h > log_u            # entropy too high -> increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(
            jnp.isinf(hi), beta * 2.0,
            jnp.where(jnp.isneginf(lo), beta / 2.0, (lo + hi) / 2.0),
        )
        return beta, lo, hi

    beta0 = jnp.ones(n, sqd.dtype)
    lo0 = jnp.full(n, -jnp.inf, sqd.dtype)
    hi0 = jnp.full(n, jnp.inf, sqd.dtype)
    beta, _, _ = jax.lax.fori_loop(0, max_iter, body, (beta0, lo0, hi0))
    _, p = entropy_p(beta)
    return p


def _sparse_perplexity_rows(sqd: np.ndarray, perplexity: float,
                            max_iter: int = 50) -> np.ndarray:
    """Per-row precision calibration over SPARSE neighborhoods: ``sqd`` is
    [n, k] squared distances to each row's k nearest neighbors. Returns the
    conditional p_{j|i} over those k entries (rows sum to 1). Same bisection
    as `_binary_search_perplexity`, vectorized in numpy on [n, k]."""
    n = sqd.shape[0]
    log_u = np.log(perplexity)
    beta = np.ones(n)
    lo = np.full(n, -np.inf)
    hi = np.full(n, np.inf)
    p = np.zeros_like(sqd)
    for _ in range(max_iter):
        p = np.exp(-sqd * beta[:, None])
        sum_p = np.maximum(p.sum(axis=1), 1e-12)
        h = np.log(sum_p) + beta * (sqd * p).sum(axis=1) / sum_p
        too_high = h > log_u
        lo = np.where(too_high, beta, lo)
        hi = np.where(too_high, hi, beta)
        beta = np.where(
            np.isinf(hi), beta * 2.0,
            np.where(np.isneginf(lo), beta / 2.0, (lo + hi) / 2.0))
    p = np.exp(-sqd * beta[:, None])
    return p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)


@functools.partial(jax.jit, static_argnames=("n_iter", "stop_lying_iter",
                                              "switch_momentum_iter"))
def _tsne_optimize(p, y0, learning_rate, momentum_init, momentum_final,
                   n_iter: int, stop_lying_iter: int,
                   switch_momentum_iter: int = 20, exaggeration: float = 4.0):
    n = p.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def grad_kl(y, pmat):
        sqd = (
            jnp.sum(y * y, axis=1, keepdims=True)
            - 2.0 * y @ y.T
            + jnp.sum(y * y, axis=1)[None, :]
        )
        num = 1.0 / (1.0 + sqd)                    # student-t kernel
        num = jnp.where(eye, 0.0, num)
        q = jnp.maximum(num / jnp.sum(num), 1e-12)
        pq = (pmat - q) * num                      # [n,n]
        # dY_i = 4 * sum_j pq_ij (y_i - y_j) == 4*(diag(row_sums) - pq) @ y
        return 4.0 * ((jnp.sum(pq, axis=1)[:, None] * y) - pq @ y)

    def body(i, carry):
        y, vel, gains = carry
        lying = i < stop_lying_iter
        pmat = jnp.where(lying, p * exaggeration, p)
        g = grad_kl(y, pmat)
        momentum = jnp.where(i < switch_momentum_iter, momentum_init,
                             momentum_final)
        same_sign = (g > 0) == (vel > 0)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
        )
        vel = momentum * vel - learning_rate * gains * g
        y = y + vel
        y = y - jnp.mean(y, axis=0, keepdims=True)
        return y, vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0))
    )
    return y


class Tsne:
    """Exact t-SNE (reference plot/Tsne.java surface): ``fit_transform(X)``
    returns the [n, n_components] embedding."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 1000,
                 stop_lying_iteration: int = 250, momentum: float = 0.5,
                 final_momentum: float = 0.8, seed: int = 12345,
                 switch_momentum_iteration: int = 20,
                 exaggeration: float = 4.0):
        # reference Tsne.java defaults differ (switchMomentumIteration=100;
        # classic BH-tSNE uses 12x early exaggeration) — both are exposed
        # here and shared by the exact and barnes_hut paths
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.stop_lying_iteration = stop_lying_iteration
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.exaggeration = exaggeration
        self.seed = seed
        self.embedding_: Optional[np.ndarray] = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        perp = min(self.perplexity, max((n - 1) / 3.0, 2.0))
        sqd = jnp.asarray(
            pairwise_distance(x, x, "sqeuclidean")
        )
        p_cond = _binary_search_perplexity(sqd, jnp.float32(perp))
        p = (p_cond + p_cond.T) / (2.0 * n)
        p = jnp.maximum(p, 1e-12)
        rs = np.random.RandomState(self.seed)
        y0 = jnp.asarray(rs.randn(n, self.n_components).astype(np.float32) * 1e-2)
        y = _tsne_optimize(
            p, y0, jnp.float32(self.learning_rate), jnp.float32(self.momentum),
            jnp.float32(self.final_momentum), self.n_iter, self.stop_lying_iteration,
            switch_momentum_iter=self.switch_momentum_iteration,
            exaggeration=float(self.exaggeration),
        )
        self.embedding_ = np.asarray(y)
        return self.embedding_


class BarnesHutTsne(Tsne):
    """Reference BarnesHutTsne.java:65 surface. ``method="exact"`` (default)
    runs the fused-jit exact gradient — faster than tree pruning at reference
    scale on TPU (module docstring). ``method="barnes_hut"`` runs a genuine
    host-side Barnes-Hut loop over `clustering/sptree.SpTree` with sparse
    top-k input similarities, honoring ``theta`` — for when n^2 terms
    genuinely cannot fit."""

    def __init__(self, theta: float = 0.5, method: str = "exact", **kw):
        super().__init__(**kw)
        self.theta = theta
        if method not in ("exact", "barnes_hut"):
            raise ValueError(f"method must be 'exact' or 'barnes_hut': {method!r}")
        self.method = method

    def fit(self, x) -> "BarnesHutTsne":
        self.fit_transform(x)
        return self

    def fit_transform(self, x) -> np.ndarray:
        if self.method == "exact":
            return super().fit_transform(x)
        from deeplearning4j_tpu.clustering.sptree import barnes_hut_gradient

        x = np.asarray(x, np.float32)
        n = x.shape[0]
        perp = min(self.perplexity, max((n - 1) / 3.0, 2.0))
        # Sparse input similarities over the 3*perplexity nearest neighbors
        # only (standard BH-tSNE input sparsity): O(n*k) memory end to end —
        # the dense n^2 path would defeat the point of this method. The kNN
        # itself is the chunked MXU top-k kernel.
        k = min(n - 1, max(int(3 * perp), 2))
        from deeplearning4j_tpu.clustering.knn import knn_search

        nbr_idx, nbr_sqd = knn_search(x, x, k + 1, metric="sqeuclidean",
                                      chunk_size=65536)
        # Drop the self-match by index (not "column 0"): among coincident
        # points top_k tie-breaks by index, so a high-index duplicate's own
        # row index can be ABSENT from its k+1 — then every returned
        # neighbor is a genuine distance-0 neighbor and we drop the worst
        # column instead.
        rows = np.arange(n)
        is_self = nbr_idx == rows[:, None]
        self_col = np.where(is_self.any(axis=1),
                            np.argmax(is_self, axis=1), k)
        keep_cols = np.ones_like(nbr_idx, dtype=bool)
        keep_cols[rows, self_col] = False
        nbr_idx = nbr_idx[keep_cols].reshape(n, k)
        sqd = nbr_sqd[keep_cols].reshape(n, k).astype(np.float64)
        p_rows = _sparse_perplexity_rows(sqd, perp)          # [n, k]
        # symmetrize P over the union pattern: P_ij = (p_i|j + p_j|i)/(2n)
        # with the missing direction contributing 0 — attraction must stay
        # conservative or the BH loop diverges (one-sided truncation
        # rotates). Vectorized COO -> coalesced CSR (no Python pair loops).
        src = np.repeat(rows, k)
        dst = nbr_idx.ravel().astype(np.int64)
        v = p_rows.ravel() / (2.0 * n)
        key = np.concatenate([src * n + dst, dst * n + src])
        vals2 = np.concatenate([v, v])
        uniq, inv = np.unique(key, return_inverse=True)
        val_p = np.bincount(inv, weights=vals2, minlength=uniq.size)
        col_p = uniq % n
        row_p = np.zeros(n + 1, np.int64)
        np.cumsum(np.bincount(uniq // n, minlength=n), out=row_p[1:])
        val_p /= max(val_p.sum(), 1e-12)

        rs = np.random.RandomState(self.seed)
        y = rs.randn(n, self.n_components) * 1e-2
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        # auto-capped learning rate (Belkina et al. 2019: eta ~ n/exaggeration,
        # floored at 50): the momentum+gains loop oscillates on small n when
        # driven at the dense-path default of 200
        lr = min(self.learning_rate, max(n / self.exaggeration, 50.0))
        for it in range(self.n_iter):
            lying = it < self.stop_lying_iteration
            g = barnes_hut_gradient(
                y, row_p, col_p,
                val_p * (self.exaggeration if lying else 1.0), self.theta)
            momentum = (self.momentum if it < self.switch_momentum_iteration
                        else self.final_momentum)
            same_sign = (g > 0) == (vel > 0)
            gains = np.maximum(np.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
            vel = momentum * vel - lr * gains * g
            y = y + vel
            y -= y.mean(axis=0, keepdims=True)
        self.embedding_ = y.astype(np.float32)
        return self.embedding_
