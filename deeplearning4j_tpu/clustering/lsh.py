"""Random-projection (signed) LSH for cosine distance.

Capability parity with the reference's clustering/lsh/RandomProjectionLSH.java
(hash/makeIndex/bucket/search for the cosine distance, with entropy-LSH
query perturbation). TPU-first: hashing is one [N,D]x[D,H] matmul + sign;
bucket matching is a jitted Hamming-agreement reduction over all tables at
once instead of per-table Java loops.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.knn import knn_search, pairwise_distance


@functools.partial(jax.jit, static_argnames=())
def _signs(data, proj):
    return (data @ proj >= 0.0).astype(jnp.uint8)  # [N, tables*hash_len]


@functools.partial(jax.jit, static_argnames=("num_tables", "hash_length"))
def _bucket_mask(index_hash, query_hash, num_tables: int, hash_length: int):
    """Row i is in the query's bucket iff SOME table agrees on all bits."""
    ih = index_hash.reshape(-1, num_tables, hash_length)
    qh = query_hash.reshape(num_tables, hash_length)
    agree = jnp.all(ih == qh[None], axis=2)          # [N, tables]
    return jnp.any(agree, axis=1)                    # [N]


class RandomProjectionLSH:
    """``RandomProjectionLSH(hash_length, num_tables, in_dimension, radius)``
    (reference RandomProjectionLSH.java:75). ``radius`` drives entropy-LSH
    perturbation sampling in ``entropy``; search falls back to exact scan
    when a bucket is empty (the reference raises — we degrade gracefully and
    stay exact)."""

    def __init__(self, hash_length: int, num_tables: int, in_dimension: int,
                 radius: float = 0.1, seed: int = 12345):
        self.hash_length = int(hash_length)
        self.num_tables = int(num_tables)
        self.in_dimension = int(in_dimension)
        self.radius = float(radius)
        rs = np.random.RandomState(seed)
        self.projection = jnp.asarray(
            rs.randn(in_dimension, num_tables * hash_length).astype(np.float32)
            / np.sqrt(in_dimension)
        )
        self._rs = rs
        self.index_data: Optional[np.ndarray] = None
        self.index_hash: Optional[jnp.ndarray] = None

    # -- hashing -----------------------------------------------------------
    def hash(self, data) -> np.ndarray:
        data = np.atleast_2d(np.asarray(data, np.float32))
        return np.asarray(_signs(jnp.asarray(data), self.projection))

    def entropy(self, x) -> np.ndarray:
        """Entropy-LSH query offsets: points sampled on the sphere of radius
        ``radius`` around x (reference RandomProjectionLSH.entropy:106)."""
        x = np.asarray(x, np.float32).reshape(-1)
        pert = self._rs.randn(self.num_tables, x.shape[0]).astype(np.float32)
        pert /= np.maximum(np.linalg.norm(pert, axis=1, keepdims=True), 1e-12)
        return x[None, :] + self.radius * pert

    # -- index -------------------------------------------------------------
    def make_index(self, data) -> None:
        self.index_data = np.asarray(data, np.float32)
        self.index_hash = jnp.asarray(self.hash(self.index_data))

    def _require_index(self):
        if self.index_data is None:
            raise RuntimeError("call make_index(data) first")

    def bucket(self, query) -> np.ndarray:
        """Boolean row mask of index points sharing a hash bucket with the
        query under ANY table, including entropy perturbations."""
        self._require_index()
        qs = np.vstack([np.atleast_2d(np.asarray(query, np.float32)),
                        self.entropy(query)])
        mask = np.zeros(self.index_data.shape[0], bool)
        for qh in self.hash(qs):
            mask |= np.asarray(
                _bucket_mask(self.index_hash, jnp.asarray(qh),
                             self.num_tables, self.hash_length)
            )
        return mask

    # -- search ------------------------------------------------------------
    def search(self, query, k: Optional[int] = None,
               max_range: Optional[float] = None) -> np.ndarray:
        """Bucketed cosine-distance search: ``k`` nearest (search(query, k),
        reference :212) or all within ``max_range`` (search(query, maxRange),
        reference :191). Returns the matching index rows, nearest first."""
        self._require_index()
        mask = self.bucket(query)
        cand_idx = np.nonzero(mask)[0]
        if cand_idx.size == 0:
            cand_idx = np.arange(self.index_data.shape[0])
        cand = self.index_data[cand_idx]
        d = np.asarray(
            pairwise_distance(np.atleast_2d(np.asarray(query, np.float32)),
                              cand, "cosinedistance")
        )[0]
        order = np.argsort(d)
        if k is not None:
            order = order[: min(k, order.size)]
        elif max_range is not None:
            order = order[d[order] <= max_range]
        return self.index_data[cand_idx[order]]
