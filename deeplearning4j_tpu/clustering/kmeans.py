"""K-Means clustering, jitted Lloyd iterations.

Capability parity with the reference's
clustering/kmeans/KMeansClustering.java (setup(clusterCount,
maxIterationCount, distanceFunction) -> applyTo(points) -> ClusterSet) —
re-designed TPU-first: the whole assignment+update iteration is ONE jitted
program (distance matrix on the MXU, segment-sum centroid update), instead
of the reference's per-point Java loops over Cluster objects.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.knn import pairwise_distance


@dataclass
class Cluster:
    """One cluster of a ClusterSet (reference clustering/cluster/Cluster.java)."""

    center: np.ndarray
    point_indices: np.ndarray

    @property
    def count(self) -> int:
        return int(len(self.point_indices))


@dataclass
class ClusterSet:
    """Result container (reference clustering/cluster/ClusterSet.java)."""

    centers: np.ndarray            # [k, d]
    assignments: np.ndarray        # [n] cluster id per point
    distances: np.ndarray          # [n] distance to own center
    distance_function: str = "euclidean"
    clusters: List[Cluster] = field(default_factory=list)

    def __post_init__(self):
        if not self.clusters:
            self.clusters = [
                Cluster(self.centers[c], np.nonzero(self.assignments == c)[0])
                for c in range(len(self.centers))
            ]

    def nearest_cluster(self, point) -> int:
        d = np.asarray(
            pairwise_distance(np.atleast_2d(point), self.centers, self.distance_function)
        )[0]
        return int(np.argmin(d))


@functools.partial(jax.jit, static_argnames=("metric",))
def _lloyd_step(points, centers, metric):
    """One Lloyd iteration: assign + recompute. Empty clusters keep their
    previous center (reference keeps the cluster alive too)."""
    d = pairwise_distance(points, centers, metric)
    assign = jnp.argmin(d, axis=1)
    k = centers.shape[0]
    one_hot = jax.nn.one_hot(assign, k, dtype=points.dtype)      # [n, k]
    counts = jnp.sum(one_hot, axis=0)                            # [k]
    sums = one_hot.T @ points                                    # [k, d]
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), centers
    )
    shift = jnp.max(jnp.linalg.norm(new_centers - centers, axis=1))
    mind = jnp.min(d, axis=1)
    return new_centers, assign, mind, shift


def _kmeanspp_init(points: np.ndarray, k: int, metric: str, rs: np.random.RandomState):
    """k-means++ seeding (D^2 sampling) — better than the reference's random
    row picks, same contract."""
    n = points.shape[0]
    centers = [points[rs.randint(n)]]
    d2 = None
    for _ in range(1, k):
        d = np.asarray(pairwise_distance(points, np.stack(centers), metric)).min(axis=1)
        d2 = d * d
        tot = d2.sum()
        if tot <= 0:
            centers.append(points[rs.randint(n)])
            continue
        centers.append(points[rs.choice(n, p=d2 / tot)])
    return np.stack(centers)


class KMeansClustering:
    """``KMeansClustering.setup(k, max_iters, distance_fn)`` then
    ``apply_to(points)`` (reference KMeansClustering.java:52)."""

    def __init__(self, cluster_count: int, max_iteration_count: int = 100,
                 distance_function: str = "euclidean", tolerance: float = 1e-4,
                 seed: int = 12345):
        if distance_function.lower() in ("cosinesimilarity", "dot"):
            raise ValueError(
                "k-means needs a distance (smaller=closer); use 'cosinedistance'"
            )
        self.k = int(cluster_count)
        self.max_iterations = int(max_iteration_count)
        self.distance_function = distance_function
        self.tolerance = float(tolerance)
        self.seed = seed

    @staticmethod
    def setup(cluster_count: int, max_iteration_count: int = 100,
              distance_function: str = "euclidean", **kw) -> "KMeansClustering":
        return KMeansClustering(cluster_count, max_iteration_count,
                                distance_function, **kw)

    def apply_to(self, points) -> ClusterSet:
        points = np.asarray(points, np.float32)
        if points.shape[0] < self.k:
            raise ValueError(f"need >= {self.k} points, got {points.shape[0]}")
        rs = np.random.RandomState(self.seed)
        centers = jnp.asarray(_kmeanspp_init(points, self.k, self.distance_function, rs))
        pts = jnp.asarray(points)
        assign = mind = None
        for _ in range(self.max_iterations):
            centers, assign, mind, shift = _lloyd_step(pts, centers, self.distance_function)
            if float(shift) < self.tolerance:
                break
        return ClusterSet(
            centers=np.asarray(centers),
            assignments=np.asarray(assign),
            distances=np.asarray(mind),
            distance_function=self.distance_function,
        )
