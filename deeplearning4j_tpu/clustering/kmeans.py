"""K-Means clustering, jitted Lloyd iterations.

Capability parity with the reference's
clustering/kmeans/KMeansClustering.java (setup(clusterCount,
maxIterationCount, distanceFunction) -> applyTo(points) -> ClusterSet) —
re-designed TPU-first: the whole assignment+update iteration is ONE jitted
program (distance matrix on the MXU, segment-sum centroid update), instead
of the reference's per-point Java loops over Cluster objects.

Both jitted sites here are shape-bucketed (``utils/bucketing``): the point
count is padded up the shared ladder and carried as a *dynamic* validity
scalar, so IVF index builds (``search/index.py``) that sweep corpus sizes
reuse a handful of executables instead of retracing per size. Compiles are
recorded through ``bucketing.record_trace`` ("kmeans.lloyd" /
"kmeans.assign") so the retrace guard and bench snapshots see index builds.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.knn import pairwise_distance
from deeplearning4j_tpu.utils import bucketing


@dataclass
class Cluster:
    """One cluster of a ClusterSet (reference clustering/cluster/Cluster.java)."""

    center: np.ndarray
    point_indices: np.ndarray

    @property
    def count(self) -> int:
        return int(len(self.point_indices))


@dataclass
class ClusterSet:
    """Result container (reference clustering/cluster/ClusterSet.java)."""

    centers: np.ndarray            # [k, d]
    assignments: np.ndarray        # [n] cluster id per point
    distances: np.ndarray          # [n] distance to own center
    distance_function: str = "euclidean"
    clusters: List[Cluster] = field(default_factory=list)

    def __post_init__(self):
        if not self.clusters:
            self.clusters = [
                Cluster(self.centers[c], np.nonzero(self.assignments == c)[0])
                for c in range(len(self.centers))
            ]

    def nearest_cluster(self, point) -> int:
        d = np.asarray(
            pairwise_distance(np.atleast_2d(point), self.centers, self.distance_function)
        )[0]
        return int(np.argmin(d))


@functools.partial(jax.jit, static_argnames=("metric",))
def _lloyd_step(points, centers, n_valid, metric):
    """One Lloyd iteration: assign + recompute. Empty clusters keep their
    previous center (reference keeps the cluster alive too). Rows at or past
    ``n_valid`` are bucket padding: they still get an argmin assignment (the
    caller slices them off) but a validity mask zeroes them out of the
    centroid sums, so the padded update equals the unpadded one exactly."""
    bucketing.telemetry().record_trace("kmeans.lloyd", points.shape)
    d = pairwise_distance(points, centers, metric)
    assign = jnp.argmin(d, axis=1)
    k = centers.shape[0]
    valid = (jnp.arange(points.shape[0]) < n_valid).astype(points.dtype)
    one_hot = jax.nn.one_hot(assign, k, dtype=points.dtype) * valid[:, None]
    counts = jnp.sum(one_hot, axis=0)                            # [k]
    sums = one_hot.T @ points                                    # [k, d]
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), centers
    )
    shift = jnp.max(jnp.linalg.norm(new_centers - centers, axis=1))
    mind = jnp.min(d, axis=1)
    return new_centers, assign, mind, shift


@functools.partial(jax.jit, static_argnames=("metric",))
def _assign_step(points, centers, metric):
    """Assignment-only site: nearest center id + distance per row. Row
    independent, so bucket padding needs no mask — padded rows are dead
    compute sliced off by the caller."""
    bucketing.telemetry().record_trace("kmeans.assign", points.shape)
    d = pairwise_distance(points, centers, metric)
    return jnp.argmin(d, axis=1), jnp.min(d, axis=1)


def assign_points(points, centers, metric: str = "euclidean",
                  chunk_rows: int = 16384) -> Tuple[np.ndarray, np.ndarray]:
    """Nearest-center assignment for a full corpus, chunked and bucketed.

    Chunking caps the [rows, k] distance matrix (16384×512 f32 ≈ 32 MB);
    each chunk's leading axis is padded up the shared ladder so corpus-size
    sweeps during IVF builds hit a handful of "kmeans.assign" executables.
    Returns ``(assign, distance)`` as host arrays of length ``len(points)``.
    """
    points = np.asarray(points, np.float32)
    # host-side API: callers (IVF build, ClusterSet) consume numpy — the
    # pulls below are the contract, not accidental syncs
    centers = jnp.asarray(np.asarray(centers, np.float32))  # graftlint: disable=host-sync
    n = points.shape[0]
    ladder = bucketing.ladder_from_env()
    tel = bucketing.telemetry()
    assigns, dists = [], []
    for lo in range(0, n, chunk_rows):
        chunk = points[lo:lo + chunk_rows]
        rows = chunk.shape[0]
        target = ladder.bucket(rows) if bucketing.bucketing_enabled() else rows
        tel.record_hit("kmeans.assign", rows, target)
        padded = bucketing.pad_rows_zero(chunk, target)
        a, d = _assign_step(jnp.asarray(padded), centers, metric)
        assigns.append(np.asarray(a[:rows]))  # graftlint: disable=host-sync
        dists.append(np.asarray(d[:rows]))  # graftlint: disable=host-sync
    if not assigns:
        return np.zeros(0, np.int32), np.zeros(0, np.float32)
    return np.concatenate(assigns), np.concatenate(dists)


def _kmeanspp_init(points: np.ndarray, k: int, metric: str, rs: np.random.RandomState):
    """k-means++ seeding (D^2 sampling) — better than the reference's random
    row picks, same contract."""
    n = points.shape[0]
    centers = [points[rs.randint(n)]]
    d2 = None
    for _ in range(1, k):
        d = np.asarray(pairwise_distance(points, np.stack(centers), metric)).min(axis=1)
        d2 = d * d
        tot = d2.sum()
        if tot <= 0:
            centers.append(points[rs.randint(n)])
            continue
        centers.append(points[rs.choice(n, p=d2 / tot)])
    return np.stack(centers)


def _random_init(points: np.ndarray, k: int, rs: np.random.RandomState):
    """Random distinct-row seeding (the reference's own strategy). O(k) vs
    k-means++'s O(n·k²) distance work — the right trade for IVF coarse
    quantizers where k is large and Lloyd refines anyway."""
    idx = rs.choice(points.shape[0], size=k, replace=False)
    return points[idx].copy()


class KMeansClustering:
    """``KMeansClustering.setup(k, max_iters, distance_fn)`` then
    ``apply_to(points)`` (reference KMeansClustering.java:52)."""

    def __init__(self, cluster_count: int, max_iteration_count: int = 100,
                 distance_function: str = "euclidean", tolerance: float = 1e-4,
                 seed: int = 12345, init: str = "kmeanspp"):
        if distance_function.lower() in ("cosinesimilarity", "dot"):
            raise ValueError(
                "k-means needs a distance (smaller=closer); use 'cosinedistance'"
            )
        if init not in ("kmeanspp", "random"):
            raise ValueError(f"init must be 'kmeanspp' or 'random', got {init!r}")
        self.k = int(cluster_count)
        self.max_iterations = int(max_iteration_count)
        self.distance_function = distance_function
        self.tolerance = float(tolerance)
        self.seed = seed
        self.init = init

    @staticmethod
    def setup(cluster_count: int, max_iteration_count: int = 100,
              distance_function: str = "euclidean", **kw) -> "KMeansClustering":
        return KMeansClustering(cluster_count, max_iteration_count,
                                distance_function, **kw)

    def apply_to(self, points) -> ClusterSet:
        points = np.asarray(points, np.float32)
        n = points.shape[0]
        if n < self.k:
            raise ValueError(f"need >= {self.k} points, got {n}")
        rs = np.random.RandomState(self.seed)
        if self.init == "random":
            centers = jnp.asarray(_random_init(points, self.k, rs))
        else:
            centers = jnp.asarray(
                _kmeanspp_init(points, self.k, self.distance_function, rs))
        ladder = bucketing.ladder_from_env()
        target = ladder.bucket(n) if bucketing.bucketing_enabled() else n
        bucketing.telemetry().record_hit("kmeans.lloyd", n, target)
        pts = jnp.asarray(bucketing.pad_rows_zero(points, target))
        n_valid = jnp.int32(n)
        assign = mind = None
        for _ in range(self.max_iterations):
            centers, assign, mind, shift = _lloyd_step(
                pts, centers, n_valid, self.distance_function)
            if float(shift) < self.tolerance:
                break
        # ClusterSet is a host-side result object — pulling once at the end
        # of the fit is the API, not a hot-path sync
        return ClusterSet(
            centers=np.asarray(centers),
            assignments=np.asarray(assign[:n]),  # graftlint: disable=host-sync
            distances=np.asarray(mind[:n]),  # graftlint: disable=host-sync
            distance_function=self.distance_function,
        )
