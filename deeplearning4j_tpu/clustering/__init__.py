"""Nearest-neighbors + clustering suite (TPU-native).

Capability parity with the reference's
deeplearning4j-nearestneighbors-parent/nearestneighbor-core
(clustering/vptree/VPTree.java:48, kdtree/KDTree.java, kmeans/KMeansClustering.java,
lsh/RandomProjectionLSH.java) and deeplearning4j-core's plot/BarnesHutTsne.java:65.

TPU-first redesign (SURVEY.md §7 "hard parts"): the reference's trees are
pointer-chasing CPU structures; on TPU the same exact-search capability is a
batched brute-force top-k (one fused matmul + top_k per corpus chunk, MXU
friendly, streamed over HBM). VPTree/KDTree remain as exact-API shims over
that kernel so reference users find the classes they expect.
"""

from deeplearning4j_tpu.clustering.knn import knn_search, pairwise_distance
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering, Cluster, ClusterSet
from deeplearning4j_tpu.clustering.lsh import RandomProjectionLSH
from deeplearning4j_tpu.clustering.server import NearestNeighborsServer
from deeplearning4j_tpu.clustering.sptree import QuadTree, SpTree
from deeplearning4j_tpu.clustering.trees import KDTree, VPTree
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne, Tsne

__all__ = [
    "knn_search",
    "pairwise_distance",
    "KMeansClustering",
    "Cluster",
    "ClusterSet",
    "RandomProjectionLSH",
    "KDTree",
    "VPTree",
    "QuadTree",
    "SpTree",
    "BarnesHutTsne",
    "Tsne",
    "NearestNeighborsServer",
]
