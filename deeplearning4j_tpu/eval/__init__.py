"""Evaluation: classification / regression / ROC metrics.

Parity: the reference's eval family (eval/Evaluation.java:72,
RegressionEvaluation.java, ROC.java, ROCBinary, ROCMultiClass,
EvaluationBinary, EvaluationCalibration, ConfusionMatrix) — SURVEY.md §2.1.

Accumulation happens on the host in numpy (tiny state: confusion counts,
histograms); the heavy part (the forward pass producing predictions) runs on
TPU. Every class supports ``merge`` so evaluations computed per-shard /
per-host can be combined, the way Spark workers merge Evaluation objects.
"""

from deeplearning4j_tpu.eval.evaluation import ConfusionMatrix, Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.serde import from_json, to_json  # import runs attach()

__all__ = [
    "Evaluation",
    "ConfusionMatrix",
    "RegressionEvaluation",
    "ROC",
    "ROCBinary",
    "ROCMultiClass",
    "EvaluationBinary",
    "EvaluationCalibration",
    "to_json",
    "from_json",
]
