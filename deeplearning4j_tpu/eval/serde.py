"""JSON serde for the evaluation family.

Capability parity with the reference's eval/serde/ package (Jackson-based
``Evaluation.toJson()``/``fromJson()`` on every IEvaluation — used to ship
merged evaluations between Spark workers and persist them with models).

One recursive encoder covers the whole family: numpy arrays are tagged with
their dtype so a round-trip restores the exact accumulator types (int64
count matrices must stay int64 for ``+=`` merges), and nested evaluation
objects (ConfusionMatrix inside Evaluation, per-class ROC lists inside
ROCMultiClass) nest naturally. ``attach()`` registers ``to_json`` /
``from_json`` onto each class so the reference's per-class surface exists.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

import numpy as np

from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.evaluation import ConfusionMatrix, Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass

_CLASSES: Dict[str, Type] = {
    c.__name__: c
    for c in (Evaluation, ConfusionMatrix, RegressionEvaluation, ROC,
              ROCBinary, ROCMultiClass, EvaluationBinary,
              EvaluationCalibration)
}


def _encode(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        return {"__nd__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, np.generic):
        return v.item()
    if type(v).__name__ in _CLASSES:
        return {"__eval__": type(v).__name__,
                "state": {k: _encode(x) for k, x in v.__dict__.items()}}
    if isinstance(v, (list, tuple)):
        return [_encode(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode(x) for k, x in v.items()}
    return v


def _decode(o: Any) -> Any:
    if isinstance(o, dict):
        if "__nd__" in o:
            return np.asarray(o["__nd__"], dtype=o["dtype"])
        if "__eval__" in o:
            cls = _CLASSES[o["__eval__"]]
            inst = cls.__new__(cls)
            inst.__dict__.update(
                {k: _decode(x) for k, x in o["state"].items()})
            return inst
        return {k: _decode(x) for k, x in o.items()}
    if isinstance(o, list):
        return [_decode(x) for x in o]
    return o


def to_json(evaluation: Any) -> str:
    """Serialize any evaluation-family object to a JSON string."""
    if type(evaluation).__name__ not in _CLASSES:
        raise TypeError(f"not an evaluation class: {type(evaluation).__name__}")
    return json.dumps(_encode(evaluation))


def from_json(s: str) -> Any:
    """Restore an evaluation-family object serialized by :func:`to_json`."""
    obj = _decode(json.loads(s))
    if type(obj).__name__ not in _CLASSES:
        raise ValueError("JSON does not contain a serialized evaluation")
    return obj


def _self_to_json(self) -> str:
    return to_json(self)


@classmethod
def _cls_from_json(cls, s: str):
    obj = from_json(s)
    if not isinstance(obj, cls):
        raise ValueError(
            f"JSON holds a {type(obj).__name__}, not a {cls.__name__}")
    return obj


def attach() -> None:
    """Give every evaluation class the reference's toJson/fromJson surface."""
    for cls in _CLASSES.values():
        cls.to_json = _self_to_json
        cls.from_json = _cls_from_json


attach()
