"""Calibration evaluation.

Parity: eval/EvaluationCalibration.java — reliability diagram (per-bin mean
predicted probability vs observed fraction positive), residual-probability
histogram, and probability histograms per class.
"""

from __future__ import annotations

import numpy as np


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.rel_bins = reliability_bins
        self.hist_bins = histogram_bins
        self._alloc_done = False

    def _alloc(self, k: int):
        self.num_classes = k
        self.rel_count = np.zeros((k, self.rel_bins), dtype=np.int64)
        self.rel_pos = np.zeros((k, self.rel_bins), dtype=np.int64)
        self.rel_prob_sum = np.zeros((k, self.rel_bins), dtype=np.float64)
        self.residual_hist = np.zeros(self.hist_bins, dtype=np.int64)
        self.prob_hist = np.zeros((k, self.hist_bins), dtype=np.int64)
        self._alloc_done = True

    def eval(self, labels, predictions):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 1:
            k = predictions.shape[-1]
            onehot = np.zeros((len(labels), k))
            onehot[np.arange(len(labels)), labels.astype(int)] = 1.0
            labels = onehot
        k = labels.shape[-1]
        if not self._alloc_done:
            self._alloc(k)
        p = np.clip(predictions, 0.0, 1.0)
        rel_idx = np.minimum((p * self.rel_bins).astype(int), self.rel_bins - 1)
        pos = labels >= 0.5
        for c in range(k):
            self.rel_count[c] += np.bincount(rel_idx[:, c], minlength=self.rel_bins)
            self.rel_pos[c] += np.bincount(rel_idx[:, c][pos[:, c]], minlength=self.rel_bins)
            self.rel_prob_sum[c] += np.bincount(
                rel_idx[:, c], weights=p[:, c], minlength=self.rel_bins
            )
            h_idx = np.minimum((p[:, c] * self.hist_bins).astype(int), self.hist_bins - 1)
            self.prob_hist[c] += np.bincount(h_idx, minlength=self.hist_bins)
        # residual = |label - p| summed over classes, per example, in [0, 2] -> clip to 1
        resid = np.clip(np.abs(labels - p).mean(axis=-1), 0.0, 1.0)
        r_idx = np.minimum((resid * self.hist_bins).astype(int), self.hist_bins - 1)
        self.residual_hist += np.bincount(r_idx, minlength=self.hist_bins)

    def reliability_diagram(self, cls: int):
        """Returns (mean_predicted_prob, observed_fraction_pos) per bin."""
        cnt = np.maximum(self.rel_count[cls], 1)
        return self.rel_prob_sum[cls] / cnt, self.rel_pos[cls] / cnt

    def expected_calibration_error(self, cls: int) -> float:
        mean_p, frac_pos = self.reliability_diagram(cls)
        weights = self.rel_count[cls] / max(self.rel_count[cls].sum(), 1)
        return float(np.sum(weights * np.abs(mean_p - frac_pos)))

    def merge(self, other: "EvaluationCalibration"):
        if not other._alloc_done:
            return self
        if not self._alloc_done:
            self._alloc(other.num_classes)
        self.rel_count += other.rel_count
        self.rel_pos += other.rel_pos
        self.rel_prob_sum += other.rel_prob_sum
        self.residual_hist += other.residual_hist
        self.prob_hist += other.prob_hist
        return self
