"""Classification evaluation + confusion matrix.

Parity: eval/Evaluation.java:72 (``eval``:288, ``accuracy``:1141, ``f1``:1034,
top-N:566) and eval/ConfusionMatrix.java. Batch-vectorised: one numpy
bincount per batch instead of the reference's per-example loop.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Dense class-by-class count matrix; rows = actual, cols = predicted."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray, weight: int = 1):
        idx = actual.astype(np.int64) * self.num_classes + predicted.astype(np.int64)
        counts = np.bincount(idx, minlength=self.num_classes**2)
        self.matrix += weight * counts.reshape(self.num_classes, self.num_classes)

    def count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())

    def total(self) -> int:
        return int(self.matrix.sum())

    def merge(self, other: "ConfusionMatrix"):
        assert self.num_classes == other.num_classes
        self.matrix += other.matrix

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    """Multi-class classification metrics accumulated over batches.

    ``eval(labels, predictions)`` accepts one-hot / probability labels of
    shape [batch, classes] (or class-index vectors) and prediction
    probabilities; rank-3 time series [batch, time, classes] are flattened
    with an optional [batch, time] mask, matching the reference's
    ``evalTimeSeries``.
    """

    def __init__(self, num_classes: Optional[int] = None, labels: Optional[Sequence[str]] = None,
                 top_n: int = 1):
        self.label_names = list(labels) if labels else None
        if num_classes is None and labels is not None:
            num_classes = len(labels)
        self.num_classes = num_classes
        self.confusion: Optional[ConfusionMatrix] = (
            ConfusionMatrix(num_classes) if num_classes else None
        )
        self.top_n = top_n
        self.top_n_correct = 0
        self.top_n_total = 0
        self.examples = 0

    # -- accumulation ------------------------------------------------------
    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = n
            self.confusion = ConfusionMatrix(n)
        elif self.num_classes != n:
            raise ValueError(f"Evaluation built for {self.num_classes} classes, got {n}")

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if (labels.dtype.kind in "iu"
                and labels.ndim == predictions.ndim - 1):
            # sparse integer class labels ([B] or [B,T]) — same convention
            # the softmax+mcxent loss head accepts
            n = predictions.shape[-1]
            actual = labels.reshape(-1).astype(np.int64)
            predictions = predictions.reshape(-1, n)
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                actual, predictions = actual[keep], predictions[keep]
            self._ensure(n)
            predicted = predictions.argmax(axis=-1)
            self.confusion.add(actual, predicted)
            self.examples += len(actual)
            if self.top_n > 1:
                top = np.argsort(-predictions, axis=-1)[:, : self.top_n]
                self.top_n_correct += int(
                    (top == actual[:, None]).any(axis=-1).sum())
                self.top_n_total += len(actual)
            return
        if labels.ndim == 3:  # time series: flatten (+ mask)
            n = labels.shape[-1]
            labels = labels.reshape(-1, n)
            predictions = predictions.reshape(-1, n)
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
        elif mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]

        if labels.ndim == 2:
            n = labels.shape[-1]
            actual = labels.argmax(axis=-1)
        else:
            actual = labels.astype(np.int64)
            n = predictions.shape[-1]
        self._ensure(n)
        predicted = predictions.argmax(axis=-1)
        self.confusion.add(actual, predicted)
        self.examples += len(actual)

        if self.top_n > 1:
            top = np.argsort(-predictions, axis=-1)[:, : self.top_n]
            self.top_n_correct += int((top == actual[:, None]).any(axis=-1).sum())
            self.top_n_total += len(actual)

    # -- metrics -----------------------------------------------------------
    def _tp(self, c):
        return self.confusion.count(c, c)

    def _fp(self, c):
        return self.confusion.predicted_total(c) - self._tp(c)

    def _fn(self, c):
        return self.confusion.actual_total(c) - self._tp(c)

    def accuracy(self) -> float:
        tot = self.confusion.total()
        return float(np.trace(self.confusion.matrix)) / tot if tot else 0.0

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.top_n_total if self.top_n_total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0 or self.confusion.predicted_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        p, r = self.precision(), self.recall()
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def matthews_correlation(self, cls: int) -> float:
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = self.confusion.total() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return ((tp * tn - fp * fn) / denom) if denom else 0.0

    def false_positive_rate(self, cls: int) -> float:
        fp = self._fp(cls)
        tn = self.confusion.total() - self._tp(cls) - fp - self._fn(cls)
        return fp / (fp + tn) if (fp + tn) else 0.0

    def false_negative_rate(self, cls: int) -> float:
        fn = self._fn(cls)
        denom = fn + self._tp(cls)
        return fn / denom if denom else 0.0

    # -- merge / report ----------------------------------------------------
    def merge(self, other: "Evaluation"):
        """Combine another Evaluation (Spark-worker merge semantics,
        eval/Evaluation merge in the reference)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(other.num_classes)
        self.confusion.merge(other.confusion)
        self.examples += other.examples
        self.top_n_correct += other.top_n_correct
        self.top_n_total += other.top_n_total
        return self

    def _name(self, c):
        return self.label_names[c] if self.label_names else str(c)

    def stats(self) -> str:
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Examples:        {self.examples}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines.append("")
        lines.append("=========================Confusion Matrix=========================")
        header = "     " + " ".join(f"{self._name(c):>6}" for c in range(self.num_classes))
        lines.append(header)
        for c in range(self.num_classes):
            row = " ".join(f"{self.confusion.count(c, p):>6}" for p in range(self.num_classes))
            lines.append(f"{self._name(c):>4} {row}")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()
