"""Regression evaluation.

Parity: eval/RegressionEvaluation.java — per-column MSE, MAE, RMSE, RSE,
Pearson correlation, R²; mergeable across workers via sufficient statistics.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class RegressionEvaluation:
    """Accumulates per-column sufficient statistics so metrics are exact over
    any number of batches and mergeable across shards."""

    def __init__(self, num_columns: Optional[int] = None,
                 column_names: Optional[Sequence[str]] = None):
        if num_columns is None and column_names is not None:
            num_columns = len(column_names)
        self.column_names = list(column_names) if column_names else None
        self.n_cols = num_columns
        self._initialized = False
        if num_columns:
            self._alloc(num_columns)

    def _alloc(self, n: int):
        self.n_cols = n
        z = lambda: np.zeros(n, dtype=np.float64)
        self.count = z()
        self.sum_err_sq = z()      # sum (y - p)^2
        self.sum_abs_err = z()     # sum |y - p|
        self.sum_label = z()
        self.sum_label_sq = z()
        self.sum_pred = z()
        self.sum_pred_sq = z()
        self.sum_label_pred = z()
        self._initialized = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            n = labels.shape[-1]
            labels = labels.reshape(-1, n)
            predictions = predictions.reshape(-1, n)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        if not self._initialized:
            self._alloc(labels.shape[-1])
        err = labels - predictions
        self.count += labels.shape[0]
        self.sum_err_sq += (err**2).sum(axis=0)
        self.sum_abs_err += np.abs(err).sum(axis=0)
        self.sum_label += labels.sum(axis=0)
        self.sum_label_sq += (labels**2).sum(axis=0)
        self.sum_pred += predictions.sum(axis=0)
        self.sum_pred_sq += (predictions**2).sum(axis=0)
        self.sum_label_pred += (labels * predictions).sum(axis=0)

    # -- metrics (per column or averaged) ---------------------------------
    def _percol(self, vals, column):
        if column is not None:
            return float(vals[column])
        return float(np.mean(vals))

    def mean_squared_error(self, column: Optional[int] = None) -> float:
        return self._percol(self.sum_err_sq / np.maximum(self.count, 1), column)

    def mean_absolute_error(self, column: Optional[int] = None) -> float:
        return self._percol(self.sum_abs_err / np.maximum(self.count, 1), column)

    def root_mean_squared_error(self, column: Optional[int] = None) -> float:
        return self._percol(np.sqrt(self.sum_err_sq / np.maximum(self.count, 1)), column)

    def relative_squared_error(self, column: Optional[int] = None) -> float:
        mean_label = self.sum_label / np.maximum(self.count, 1)
        ss_tot = self.sum_label_sq - self.count * mean_label**2
        return self._percol(self.sum_err_sq / np.maximum(ss_tot, 1e-12), column)

    def pearson_correlation(self, column: Optional[int] = None) -> float:
        n = np.maximum(self.count, 1)
        cov = self.sum_label_pred - self.sum_label * self.sum_pred / n
        var_l = self.sum_label_sq - self.sum_label**2 / n
        var_p = self.sum_pred_sq - self.sum_pred**2 / n
        denom = np.sqrt(np.maximum(var_l * var_p, 1e-12))
        return self._percol(cov / denom, column)

    def r_squared(self, column: Optional[int] = None) -> float:
        mean_label = self.sum_label / np.maximum(self.count, 1)
        ss_tot = self.sum_label_sq - self.count * mean_label**2
        return self._percol(1.0 - self.sum_err_sq / np.maximum(ss_tot, 1e-12), column)

    def merge(self, other: "RegressionEvaluation"):
        if not other._initialized:
            return self
        if not self._initialized:
            self._alloc(other.n_cols)
        for attr in ("count", "sum_err_sq", "sum_abs_err", "sum_label", "sum_label_sq",
                     "sum_pred", "sum_pred_sq", "sum_label_pred"):
            setattr(self, attr, getattr(self, attr) + getattr(other, attr))
        return self

    def stats(self) -> str:
        names = self.column_names or [f"col_{i}" for i in range(self.n_cols)]
        lines = ["Column      MSE          MAE          RMSE         RSE          R^2"]
        for i, nm in enumerate(names):
            lines.append(
                f"{nm:<10} {self.mean_squared_error(i):<12.5g} {self.mean_absolute_error(i):<12.5g} "
                f"{self.root_mean_squared_error(i):<12.5g} {self.relative_squared_error(i):<12.5g} "
                f"{self.r_squared(i):<12.5g}"
            )
        return "\n".join(lines)
