"""ROC / AUC evaluation.

Parity: eval/ROC.java (720 LoC), ROCBinary.java, ROCMultiClass.java and the
curve classes in eval/curves/. Like the reference's thresholded mode, scores
are histogrammed into a fixed number of probability bins so memory is O(bins)
regardless of dataset size and merge across workers is exact; ``num_bins=0``
is the exact mode (stores all scores).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ROC:
    """Binary ROC: accumulate (probability-of-positive, label) pairs.

    With ``num_bins > 0`` counts land in uniform probability bins
    (thresholded mode, like the reference's thresholdSteps); AUC is computed
    by trapezoid over the binned ROC curve.
    """

    def __init__(self, num_bins: int = 200):
        self.num_bins = num_bins
        if num_bins > 0:
            self.pos_hist = np.zeros(num_bins, dtype=np.int64)
            self.neg_hist = np.zeros(num_bins, dtype=np.int64)
        else:
            self._scores = []
            self._labels = []

    def eval(self, labels, predictions):
        """labels: [n] {0,1} or [n,2] one-hot; predictions: [n] P(pos) or
        [n,2] probabilities (column 1 = positive, DL4J convention)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2:
            # [n,1] is a single sigmoid column (values ARE the labels);
            # argmax would map every row to class 0
            labels = labels[:, 0] if labels.shape[1] == 1 else labels.argmax(axis=-1)
        if predictions.ndim == 2:
            if predictions.shape[1] > 2:
                raise ValueError(
                    f"ROC is binary-only but predictions have {predictions.shape[1]} "
                    "columns; use ROCMultiClass for multi-class outputs"
                )
            predictions = predictions[:, 1] if predictions.shape[1] == 2 else predictions[:, 0]
        labels = labels.astype(bool)
        p = np.clip(predictions.astype(np.float64), 0.0, 1.0)
        if self.num_bins > 0:
            bins = np.minimum((p * self.num_bins).astype(np.int64), self.num_bins - 1)
            self.pos_hist += np.bincount(bins[labels], minlength=self.num_bins)
            self.neg_hist += np.bincount(bins[~labels], minlength=self.num_bins)
        else:
            self._scores.append(p)
            self._labels.append(labels)

    def _counts(self):
        """Exact mode: raw concatenated (scores, labels) — callers sort."""
        if self.num_bins > 0:
            return self.pos_hist, self.neg_hist
        scores = np.concatenate(self._scores) if self._scores else np.zeros(0)
        labels = np.concatenate(self._labels) if self._labels else np.zeros(0, bool)
        return scores, labels

    def roc_curve(self):
        """Returns (fpr, tpr) arrays from highest threshold to lowest."""
        if self.num_bins > 0:
            # cumulative from the top bin down
            pos = self.pos_hist[::-1].cumsum().astype(np.float64)
            neg = self.neg_hist[::-1].cumsum().astype(np.float64)
            tp_total = max(pos[-1], 1.0)
            fp_total = max(neg[-1], 1.0)
            tpr = np.concatenate([[0.0], pos / tp_total])
            fpr = np.concatenate([[0.0], neg / fp_total])
            return fpr, tpr
        scores, labels = self._counts()
        order = np.argsort(-scores)
        labels = labels[order]
        tps = np.cumsum(labels).astype(np.float64)
        fps = np.cumsum(~labels).astype(np.float64)
        tp_total = max(tps[-1] if len(tps) else 0.0, 1.0)
        fp_total = max(fps[-1] if len(fps) else 0.0, 1.0)
        tpr = np.concatenate([[0.0], tps / tp_total])
        fpr = np.concatenate([[0.0], fps / fp_total])
        return fpr, tpr

    def calculate_auc(self) -> float:
        fpr, tpr = self.roc_curve()
        return float(np.trapezoid(tpr, fpr))

    def precision_recall_curve(self):
        if self.num_bins > 0:
            pos = self.pos_hist[::-1].cumsum().astype(np.float64)
            neg = self.neg_hist[::-1].cumsum().astype(np.float64)
            tp_total = max(pos[-1], 1.0)
            precision = pos / np.maximum(pos + neg, 1.0)
            recall = pos / tp_total
            return recall, precision
        scores, labels = self._counts()
        order = np.argsort(-scores)
        labels = labels[order]
        tps = np.cumsum(labels).astype(np.float64)
        fps = np.cumsum(~labels).astype(np.float64)
        tp_total = max(tps[-1] if len(tps) else 0.0, 1.0)
        precision = tps / np.maximum(tps + fps, 1.0)
        recall = tps / tp_total
        return recall, precision

    def calculate_auprc(self) -> float:
        recall, precision = self.precision_recall_curve()
        return float(np.trapezoid(precision, recall))

    def merge(self, other: "ROC"):
        if self.num_bins > 0 and other.num_bins == self.num_bins:
            self.pos_hist += other.pos_hist
            self.neg_hist += other.neg_hist
        elif self.num_bins == 0 and other.num_bins == 0:
            self._scores.extend(other._scores)
            self._labels.extend(other._labels)
        else:
            raise ValueError("Cannot merge ROC with different num_bins")
        return self


class ROCBinary:
    """Per-output-column independent binary ROC (ROCBinary.java): for
    multi-label sigmoid outputs [n, k]."""

    def __init__(self, num_bins: int = 200):
        self.num_bins = num_bins
        self.per_column = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        k = labels.shape[-1]
        if self.per_column is None:
            self.per_column = [ROC(self.num_bins) for _ in range(k)]
        for c in range(k):
            lab, pred = labels[:, c], predictions[:, c]
            if mask is not None:
                keep = np.asarray(mask)[:, c] > 0 if np.asarray(mask).ndim == 2 else np.asarray(mask) > 0
                lab, pred = lab[keep], pred[keep]
            self.per_column[c].eval(lab, pred)

    def calculate_auc(self, column: int = 0) -> float:
        return self.per_column[column].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.per_column]))

    def merge(self, other: "ROCBinary"):
        if other.per_column is None:
            return self
        if self.per_column is None:
            self.per_column = [ROC(self.num_bins) for _ in other.per_column]
        for a, b in zip(self.per_column, other.per_column):
            a.merge(b)
        return self


class ROCMultiClass:
    """One-vs-all ROC per class (ROCMultiClass.java): softmax outputs [n, k]."""

    def __init__(self, num_bins: int = 200):
        self.num_bins = num_bins
        self.per_class = None

    def eval(self, labels, predictions):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            k = predictions.shape[-1]
            onehot = np.zeros((len(labels), k))
            onehot[np.arange(len(labels)), labels.astype(int)] = 1.0
            labels = onehot
        k = labels.shape[-1]
        if self.per_class is None:
            self.per_class = [ROC(self.num_bins) for _ in range(k)]
        for c in range(k):
            self.per_class[c].eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self.per_class[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.per_class]))

    def merge(self, other: "ROCMultiClass"):
        if other.per_class is None:
            return self
        if self.per_class is None:
            self.per_class = [ROC(self.num_bins) for _ in other.per_class]
        for a, b in zip(self.per_class, other.per_class):
            a.merge(b)
        return self
