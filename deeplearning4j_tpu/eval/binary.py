"""Multi-label binary evaluation.

Parity: eval/EvaluationBinary.java — per-output-column binary counts
(TP/FP/TN/FN at threshold 0.5) for sigmoid multi-label heads.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class EvaluationBinary:
    def __init__(self, num_columns: Optional[int] = None, threshold: float = 0.5,
                 column_names: Optional[Sequence[str]] = None):
        self.threshold = threshold
        self.column_names = list(column_names) if column_names else None
        self.tp = self.fp = self.tn = self.fn = None
        if num_columns:
            self._alloc(num_columns)

    def _alloc(self, k: int):
        self.tp = np.zeros(k, dtype=np.int64)
        self.fp = np.zeros(k, dtype=np.int64)
        self.tn = np.zeros(k, dtype=np.int64)
        self.fn = np.zeros(k, dtype=np.int64)

    @property
    def num_columns(self):
        return len(self.tp) if self.tp is not None else 0

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        if labels.ndim == 3:
            k = labels.shape[-1]
            labels = labels.reshape(-1, k)
            predictions = predictions.reshape(-1, k)
            if mask is not None and np.asarray(mask).ndim == 2:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
                mask = None
        if self.tp is None:
            self._alloc(labels.shape[-1])
        pred = predictions >= self.threshold
        lab = labels >= 0.5
        w = np.ones(labels.shape, dtype=bool)
        if mask is not None:
            m = np.asarray(mask)
            w = (m if m.ndim == 2 else m[:, None] * np.ones_like(labels)) > 0
        self.tp += (pred & lab & w).sum(axis=0)
        self.fp += (pred & ~lab & w).sum(axis=0)
        self.tn += (~pred & ~lab & w).sum(axis=0)
        self.fn += (~pred & lab & w).sum(axis=0)

    def accuracy(self, col: int) -> float:
        tot = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float(self.tp[col] + self.tn[col]) / tot if tot else 0.0

    def precision(self, col: int) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col]) / d if d else 0.0

    def recall(self, col: int) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col]) / d if d else 0.0

    def f1(self, col: int) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def average_accuracy(self) -> float:
        return float(np.mean([self.accuracy(c) for c in range(self.num_columns)]))

    def average_f1(self) -> float:
        return float(np.mean([self.f1(c) for c in range(self.num_columns)]))

    def merge(self, other: "EvaluationBinary"):
        if other.tp is None:
            return self
        if self.tp is None:
            self._alloc(other.num_columns)
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn
        return self

    def stats(self) -> str:
        names = self.column_names or [f"label_{i}" for i in range(self.num_columns)]
        lines = ["Label       Acc      Precision Recall   F1"]
        for i, nm in enumerate(names):
            lines.append(f"{nm:<11} {self.accuracy(i):<8.4f} {self.precision(i):<9.4f} "
                         f"{self.recall(i):<8.4f} {self.f1(i):<8.4f}")
        return "\n".join(lines)
