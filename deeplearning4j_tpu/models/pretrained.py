"""Pretrained-weight loading for zoo models (ZooModel.initPretrained parity).

Reference: zoo/ZooModel.java:40-52 — initPretrained(PretrainedType) resolves
a checkpoint URL, downloads into a local cache (~/.deeplearning4j), and
restores the model. This environment is air-gapped, so the cache IS the
contract: weights are resolved from ``$DL4J_TPU_HOME/models/<name>.zip``
(default ``~/.deeplearning4j_tpu``) or an explicit path, in any format
``utils/guesser.load_any`` understands (native zip, reference DL4J zip,
Keras h5).

Transplant semantics: parameters are copied per vertex/layer wherever the
name exists in both models with identical leaf shapes (the transfer-learning
scenario: a checkpoint with a different classifier head still loads the
backbone, and the mismatched head keeps its fresh initialization — this is
reported in the returned summary rather than silently).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def pretrained_cache_dir() -> str:
    root = os.environ.get("DL4J_TPU_HOME") or os.path.join(
        os.path.expanduser("~"), ".deeplearning4j_tpu")
    return os.path.join(root, "models")


def pretrained_path(name: str, cache_dir: Optional[str] = None) -> str:
    d = cache_dir or pretrained_cache_dir()
    p = os.path.join(d, f"{name}.zip")
    if not os.path.exists(p):
        raise FileNotFoundError(
            f"No cached weights for {name!r}: expected {p}. This build is "
            "air-gapped — place a checkpoint zip (native or DL4J format) "
            "there, or pass an explicit path.")
    return p


def _shapes_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return (len(la) == len(lb)
            and all(np.shape(x) == np.shape(y) for x, y in zip(la, lb)))


def _cast_like(src_tree, dst_tree):
    """Transplanted leaves take the DESTINATION dtype (a bf16 config loading
    an f32 checkpoint must stay bf16 — mixed-dtype params break the step)."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda s, d: jnp.asarray(s, dtype=d.dtype), src_tree, dst_tree)


def init_pretrained(conf, weights: Optional[str] = None, *,
                    name: Optional[str] = None,
                    cache_dir: Optional[str] = None) -> Any:
    """Build a model from ``conf`` (a MultiLayerConfiguration or
    ComputationGraphConfiguration, e.g. a zoo constructor's output) and load
    pretrained parameters into it.

    ``weights``: explicit checkpoint path; otherwise resolved from the local
    cache via ``name``. Returns the initialized model; the transplant summary
    lives on ``model.pretrained_summary`` as
    {"loaded": [...], "skipped": [...]} of vertex/layer identifiers.
    """
    from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.model import MultiLayerNetwork
    from deeplearning4j_tpu.utils.guesser import load_any

    if weights is None:
        if name is None:
            raise ValueError("init_pretrained needs `weights=` path or `name=`")
        weights = pretrained_path(name, cache_dir)

    src = load_any(weights)
    if not hasattr(src, "params"):
        raise ValueError(f"{weights!r} contains a bare configuration, not a model")

    if isinstance(conf, ComputationGraphConfiguration):
        model = ComputationGraph(conf).init()
        if not isinstance(src, ComputationGraph):
            raise ValueError(
                f"checkpoint is {type(src).__name__}, config is a ComputationGraph")
        loaded, skipped = [], []
        new_params = dict(model.params)
        new_state = dict(model.state)
        for vname in model.topo_order:
            if not jax.tree_util.tree_leaves(new_params[vname]):
                continue  # param-free vertex: neither loaded nor skipped
            if vname in src.params and _shapes_equal(src.params[vname], new_params[vname]):
                new_params[vname] = _cast_like(src.params[vname], new_params[vname])
                if vname in src.state and _shapes_equal(src.state[vname], new_state[vname]):
                    new_state[vname] = _cast_like(src.state[vname], new_state[vname])
                loaded.append(vname)
            else:
                skipped.append(vname)
        model.params, model.state = new_params, new_state
    else:
        model = MultiLayerNetwork(conf).init()
        if not isinstance(src, MultiLayerNetwork):
            raise ValueError(
                f"checkpoint is {type(src).__name__}, config is a MultiLayerNetwork")
        loaded, skipped = [], []
        new_params = list(model.params)
        new_state = list(model.state)
        for i in range(min(len(new_params), len(src.params))):
            if not jax.tree_util.tree_leaves(new_params[i]):
                continue
            if _shapes_equal(src.params[i], new_params[i]):
                new_params[i] = _cast_like(src.params[i], new_params[i])
                if _shapes_equal(src.state[i], new_state[i]):
                    new_state[i] = _cast_like(src.state[i], new_state[i])
                loaded.append(i)
            else:
                skipped.append(i)
        model.params, model.state = tuple(new_params), tuple(new_state)

    if not loaded:
        raise ValueError(
            f"init_pretrained: no layer of {weights!r} matched the config "
            "(wrong architecture?)")
    model.pretrained_summary = {"loaded": loaded, "skipped": skipped}
    return model
