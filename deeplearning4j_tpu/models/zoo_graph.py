"""Zoo architectures — the full reference set.

Parity with deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/model/:
AlexNet.java, VGG16.java, VGG19.java, ResNet50.java, GoogLeNet.java,
Darknet19.java, TinyYOLO.java, InceptionResNetV1.java, FaceNetNN4Small2.java.
Sequential nets return MultiLayerConfiguration; DAG nets (ResNet50,
GoogLeNet, InceptionResNetV1, FaceNet) return ComputationGraphConfiguration.
All NHWC (TPU tiling), all pure config — JSON round-trippable data.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from deeplearning4j_tpu.nn.graph import (
    ComputationGraphConfiguration,
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    Dense,
    DropoutLayer,
    GlobalPooling,
    LocalResponseNormalization,
    OutputLayer,
    SpaceToDepth,
    Subsampling2D,
    Yolo2OutputLayer,
    ZeroPadding2D,
)
from deeplearning4j_tpu.nn.model import MultiLayerConfiguration


def AlexNet(height: int = 224, width: int = 224, channels: int = 3,
            num_classes: int = 1000, updater=None, seed: int = 12345,
            dtype: str = "float32") -> MultiLayerConfiguration:
    """AlexNet (zoo/model/AlexNet.java): 5 conv + LRN + 3 dense."""
    return MultiLayerConfiguration(
        layers=(
            Conv2D(n_out=96, kernel=(11, 11), stride=(4, 4), activation="relu"),
            LocalResponseNormalization(),
            Subsampling2D(kernel=(3, 3), stride=(2, 2)),
            Conv2D(n_out=256, kernel=(5, 5), stride=(1, 1), convolution_mode="same",
                   activation="relu"),
            LocalResponseNormalization(),
            Subsampling2D(kernel=(3, 3), stride=(2, 2)),
            Conv2D(n_out=384, kernel=(3, 3), convolution_mode="same", activation="relu"),
            Conv2D(n_out=384, kernel=(3, 3), convolution_mode="same", activation="relu"),
            Conv2D(n_out=256, kernel=(3, 3), convolution_mode="same", activation="relu"),
            Subsampling2D(kernel=(3, 3), stride=(2, 2)),
            Dense(n_out=4096, activation="relu", dropout=0.5),
            Dense(n_out=4096, activation="relu", dropout=0.5),
            OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
        ),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "nesterov", "lr": 1e-2, "momentum": 0.9},
        seed=seed, dtype=dtype,
    )


def _vgg_block(layers, n_convs: int, n_out: int, batch_norm: bool = False):
    for _ in range(n_convs):
        if batch_norm:
            layers.append(Conv2D(n_out=n_out, kernel=(3, 3),
                                 convolution_mode="same",
                                 activation="identity", has_bias=False))
            layers.append(BatchNorm())
            layers.append(ActivationLayer(activation="relu"))
        else:
            layers.append(Conv2D(n_out=n_out, kernel=(3, 3),
                                 convolution_mode="same", activation="relu"))
    layers.append(Subsampling2D(kernel=(2, 2), stride=(2, 2)))


def VGG16(height: int = 224, width: int = 224, channels: int = 3,
          num_classes: int = 1000, updater=None, seed: int = 12345,
          dtype: str = "float32", batch_norm: bool = False,
          fc_dropout: float = 0.0,
          fc_width: int = 4096) -> MultiLayerConfiguration:
    """VGG-16 (zoo/model/VGG16.java).

    ``batch_norm=True`` inserts BatchNorm after every conv (the torchvision
    vgg16_bn variant); ``fc_dropout`` enables the classifier dropout the
    reference ships commented out (VGG16.java:147-149); ``fc_width``
    shrinks the classifier for small inputs/tests (reference: 4096)."""
    layers: list = []
    for n_convs, width_ in ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)):
        _vgg_block(layers, n_convs, width_, batch_norm=batch_norm)
    layers += [
        Dense(n_out=fc_width, activation="relu", dropout=fc_dropout),
        Dense(n_out=fc_width, activation="relu", dropout=fc_dropout),
        OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
    ]
    return MultiLayerConfiguration(
        layers=tuple(layers),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "nesterov", "lr": 1e-2, "momentum": 0.9},
        seed=seed, dtype=dtype,
    )


def VGG19(height: int = 224, width: int = 224, channels: int = 3,
          num_classes: int = 1000, updater=None, seed: int = 12345,
          dtype: str = "float32") -> MultiLayerConfiguration:
    """VGG-19 (zoo/model/VGG19.java)."""
    layers: list = []
    for n_convs, width_ in ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)):
        _vgg_block(layers, n_convs, width_)
    layers += [
        Dense(n_out=4096, activation="relu"),
        Dense(n_out=4096, activation="relu"),
        OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
    ]
    return MultiLayerConfiguration(
        layers=tuple(layers),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "nesterov", "lr": 1e-2, "momentum": 0.9},
        seed=seed, dtype=dtype,
    )


# ---------------------------------------------------------------------------
# ResNet-50 (DAG)
# ---------------------------------------------------------------------------

def _conv_bn(g, name: str, inp: str, n_out: int, kernel, stride=(1, 1),
             mode: str = "same", act: str = "relu") -> str:
    g.add_layer(f"{name}_conv", Conv2D(n_out=n_out, kernel=tuple(kernel),
                                       stride=tuple(stride), convolution_mode=mode,
                                       activation="identity", has_bias=False), inp)
    g.add_layer(f"{name}_bn", BatchNorm(), f"{name}_conv")
    if act != "identity":
        g.add_layer(f"{name}_act", ActivationLayer(activation=act), f"{name}_bn")
        return f"{name}_act"
    return f"{name}_bn"


def _bottleneck(g, name: str, inp: str, filters: Tuple[int, int, int],
                stride=(1, 1), downsample: bool = False) -> str:
    f1, f2, f3 = filters
    a = _conv_bn(g, f"{name}_a", inp, f1, (1, 1), stride)
    b = _conv_bn(g, f"{name}_b", a, f2, (3, 3))
    c = _conv_bn(g, f"{name}_c", b, f3, (1, 1), act="identity")
    if downsample:
        short = _conv_bn(g, f"{name}_ds", inp, f3, (1, 1), stride, act="identity")
    else:
        short = inp
    g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), c, short)
    g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_out"


def ResNet50(height: int = 224, width: int = 224, channels: int = 3,
             num_classes: int = 1000, updater=None, seed: int = 12345,
             dtype: str = "float32", stem: str = "conv7") -> ComputationGraphConfiguration:
    """ResNet-50 (zoo/model/ResNet50.java): conv7 + 3/4/6/3 bottleneck stages.
    BASELINE config #2.

    ``stem="conv7"`` is the reference-faithful 7x7/s2 stem.
    ``stem="space_to_depth"`` is the TPU-optimized MLPerf-style variant:
    SpaceToDepth(2) + 4x4/s1 conv — same receptive-field class and output
    shape, but the conv's contraction dim is 4*4*(4*channels) instead of
    7*7*channels, which fills the 128-lane MXU instead of running ~3/128
    occupied. Same parameter COUNT class, different layout — checkpoints
    are not interchangeable between stems."""
    g = (ComputationGraphConfiguration.builder()
         .add_inputs("in")
         .set_input_types(InputType.convolutional(height, width, channels)))
    if stem == "space_to_depth":
        g.add_layer("stem_s2d", SpaceToDepth(block=2), "in")
        stem_v = _conv_bn(g, "stem", "stem_s2d", 64, (4, 4), (1, 1))
    elif stem == "conv7":
        stem_v = _conv_bn(g, "stem", "in", 64, (7, 7), (2, 2))
    else:
        raise ValueError(f"stem must be 'conv7' or 'space_to_depth', got {stem!r}")
    g.add_layer("stem_pool", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                           convolution_mode="same"), stem_v)
    x = "stem_pool"
    stages = [
        ("s2", (64, 64, 256), 3, (1, 1)),
        ("s3", (128, 128, 512), 4, (2, 2)),
        ("s4", (256, 256, 1024), 6, (2, 2)),
        ("s5", (512, 512, 2048), 3, (2, 2)),
    ]
    for sname, filters, blocks, stride in stages:
        x = _bottleneck(g, f"{sname}b1", x, filters, stride, downsample=True)
        for i in range(1, blocks):
            x = _bottleneck(g, f"{sname}b{i + 1}", x, filters)
    g.add_layer("avgpool", GlobalPooling(pooling="avg"), x)
    g.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss="mcxent"), "avgpool")
    g.set_outputs("out")
    g.updater(updater or {"type": "adam", "lr": 1e-3})
    conf = g.build()
    conf.seed = seed
    conf.dtype = dtype
    return conf


# ---------------------------------------------------------------------------
# GoogLeNet / Inception-v1 (DAG)
# ---------------------------------------------------------------------------

def _inception(g, name: str, inp: str, f1: int, f3r: int, f3: int,
               f5r: int, f5: int, fp: int) -> str:
    g.add_layer(f"{name}_1x1", Conv2D(n_out=f1, kernel=(1, 1), activation="relu",
                                      convolution_mode="same"), inp)
    g.add_layer(f"{name}_3x3r", Conv2D(n_out=f3r, kernel=(1, 1), activation="relu",
                                       convolution_mode="same"), inp)
    g.add_layer(f"{name}_3x3", Conv2D(n_out=f3, kernel=(3, 3), activation="relu",
                                      convolution_mode="same"), f"{name}_3x3r")
    g.add_layer(f"{name}_5x5r", Conv2D(n_out=f5r, kernel=(1, 1), activation="relu",
                                       convolution_mode="same"), inp)
    g.add_layer(f"{name}_5x5", Conv2D(n_out=f5, kernel=(5, 5), activation="relu",
                                      convolution_mode="same"), f"{name}_5x5r")
    g.add_layer(f"{name}_pool", Subsampling2D(kernel=(3, 3), stride=(1, 1),
                                              convolution_mode="same"), inp)
    g.add_layer(f"{name}_poolproj", Conv2D(n_out=fp, kernel=(1, 1), activation="relu",
                                           convolution_mode="same"), f"{name}_pool")
    g.add_vertex(f"{name}_merge", MergeVertex(),
                 f"{name}_1x1", f"{name}_3x3", f"{name}_5x5", f"{name}_poolproj")
    return f"{name}_merge"


def GoogLeNet(height: int = 224, width: int = 224, channels: int = 3,
              num_classes: int = 1000, updater=None, seed: int = 12345,
              dtype: str = "float32") -> ComputationGraphConfiguration:
    """GoogLeNet / Inception-v1 (zoo/model/GoogLeNet.java): 9 inception
    modules (aux classifiers omitted, as in the reference's zoo model)."""
    g = (ComputationGraphConfiguration.builder()
         .add_inputs("in")
         .set_input_types(InputType.convolutional(height, width, channels)))
    g.add_layer("c1", Conv2D(n_out=64, kernel=(7, 7), stride=(2, 2), activation="relu",
                             convolution_mode="same"), "in")
    g.add_layer("p1", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), "c1")
    g.add_layer("n1", LocalResponseNormalization(), "p1")
    g.add_layer("c2r", Conv2D(n_out=64, kernel=(1, 1), activation="relu",
                              convolution_mode="same"), "n1")
    g.add_layer("c2", Conv2D(n_out=192, kernel=(3, 3), activation="relu",
                             convolution_mode="same"), "c2r")
    g.add_layer("n2", LocalResponseNormalization(), "c2")
    g.add_layer("p2", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), "n2")
    x = _inception(g, "i3a", "p2", 64, 96, 128, 16, 32, 32)
    x = _inception(g, "i3b", x, 128, 128, 192, 32, 96, 64)
    g.add_layer("p3", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), x)
    x = _inception(g, "i4a", "p3", 192, 96, 208, 16, 48, 64)
    x = _inception(g, "i4b", x, 160, 112, 224, 24, 64, 64)
    x = _inception(g, "i4c", x, 128, 128, 256, 24, 64, 64)
    x = _inception(g, "i4d", x, 112, 144, 288, 32, 64, 64)
    x = _inception(g, "i4e", x, 256, 160, 320, 32, 128, 128)
    g.add_layer("p4", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), x)
    x = _inception(g, "i5a", "p4", 256, 160, 320, 32, 128, 128)
    x = _inception(g, "i5b", x, 384, 192, 384, 48, 128, 128)
    g.add_layer("avgpool", GlobalPooling(pooling="avg"), x)
    g.add_layer("drop", DropoutLayer(dropout=0.4), "avgpool")
    g.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss="mcxent"), "drop")
    g.set_outputs("out")
    g.updater(updater or {"type": "adam", "lr": 1e-3})
    conf = g.build()
    conf.seed = seed
    conf.dtype = dtype
    return conf


# ---------------------------------------------------------------------------
# Darknet19 / TinyYOLO
# ---------------------------------------------------------------------------

def _dark_conv(n_out: int, kernel=(3, 3)) -> Tuple:
    return (
        Conv2D(n_out=n_out, kernel=tuple(kernel), convolution_mode="same",
               activation="identity", has_bias=False),
        BatchNorm(),
        ActivationLayer(activation="leakyrelu"),
    )


def Darknet19(height: int = 224, width: int = 224, channels: int = 3,
              num_classes: int = 1000, updater=None, seed: int = 12345,
              dtype: str = "float32") -> MultiLayerConfiguration:
    """Darknet-19 (zoo/model/Darknet19.java): 19 conv layers, BN + leaky relu."""
    L: list = []
    pool = lambda: Subsampling2D(kernel=(2, 2), stride=(2, 2))
    L += _dark_conv(32); L.append(pool())
    L += _dark_conv(64); L.append(pool())
    L += _dark_conv(128); L += _dark_conv(64, (1, 1)); L += _dark_conv(128); L.append(pool())
    L += _dark_conv(256); L += _dark_conv(128, (1, 1)); L += _dark_conv(256); L.append(pool())
    L += _dark_conv(512); L += _dark_conv(256, (1, 1)); L += _dark_conv(512)
    L += _dark_conv(256, (1, 1)); L += _dark_conv(512); L.append(pool())
    L += _dark_conv(1024); L += _dark_conv(512, (1, 1)); L += _dark_conv(1024)
    L += _dark_conv(512, (1, 1)); L += _dark_conv(1024)
    L.append(Conv2D(n_out=num_classes, kernel=(1, 1), convolution_mode="same",
                    activation="identity"))
    L.append(GlobalPooling(pooling="avg"))
    from deeplearning4j_tpu.nn.layers import LossLayer

    L.append(LossLayer(activation="softmax", loss="mcxent"))
    return MultiLayerConfiguration(
        layers=tuple(L),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "nesterov", "lr": 1e-3, "momentum": 0.9},
        seed=seed, dtype=dtype,
    )


TINY_YOLO_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                     (9.42, 5.11), (16.62, 10.52))


def TinyYOLO(height: int = 416, width: int = 416, channels: int = 3,
             num_classes: int = 20, anchors=TINY_YOLO_ANCHORS, updater=None,
             seed: int = 12345, dtype: str = "float32") -> MultiLayerConfiguration:
    """TinyYOLO v2 (zoo/model/TinyYOLO.java): darknet-tiny backbone + YOLO2
    detection head over a 13x13 grid (for 416 input)."""
    L: list = []
    pool = lambda: Subsampling2D(kernel=(2, 2), stride=(2, 2))
    for n in (16, 32, 64, 128, 256):
        L += _dark_conv(n)
        L.append(pool())
    L += _dark_conv(512)
    L.append(Subsampling2D(kernel=(2, 2), stride=(1, 1), convolution_mode="same"))
    L += _dark_conv(1024)
    L += _dark_conv(1024)
    n_anchors = len(anchors)
    L.append(Conv2D(n_out=n_anchors * (5 + num_classes), kernel=(1, 1),
                    convolution_mode="same", activation="identity"))
    L.append(Yolo2OutputLayer(boxes=tuple(tuple(a) for a in anchors)))
    return MultiLayerConfiguration(
        layers=tuple(L),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "adam", "lr": 1e-3},
        seed=seed, dtype=dtype,
    )


# ---------------------------------------------------------------------------
# InceptionResNetV1 / FaceNetNN4Small2 (face embedding nets)
# ---------------------------------------------------------------------------

def _ir_block(g, name: str, inp: str, scale_filters: Sequence[Tuple[int, tuple]],
              n_out: int) -> str:
    """Inception-resnet residual block: parallel conv towers → 1x1 projection
    → residual add → relu."""
    towers = []
    for ti, tower in enumerate(scale_filters):
        prev = inp
        for li, (f, k) in enumerate(tower):
            lname = f"{name}_t{ti}_{li}"
            g.add_layer(lname, Conv2D(n_out=f, kernel=tuple(k), activation="relu",
                                      convolution_mode="same"), prev)
            prev = lname
        towers.append(prev)
    g.add_vertex(f"{name}_cat", MergeVertex(), *towers)
    g.add_layer(f"{name}_proj", Conv2D(n_out=n_out, kernel=(1, 1),
                                       activation="identity",
                                       convolution_mode="same"), f"{name}_cat")
    g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), f"{name}_proj", inp)
    g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_out"


def InceptionResNetV1(height: int = 160, width: int = 160, channels: int = 3,
                      num_classes: int = 1001, embedding_size: int = 128,
                      n_blocks: Tuple[int, int, int] = (5, 10, 5),
                      updater=None, seed: int = 12345,
                      dtype: str = "float32") -> ComputationGraphConfiguration:
    """Inception-ResNet-v1 (zoo/model/InceptionResNetV1.java): stem +
    A/B/C residual inception stages + embedding + softmax head."""
    g = (ComputationGraphConfiguration.builder()
         .add_inputs("in")
         .set_input_types(InputType.convolutional(height, width, channels)))
    g.add_layer("stem1", Conv2D(n_out=32, kernel=(3, 3), stride=(2, 2),
                                activation="relu", convolution_mode="same"), "in")
    g.add_layer("stem2", Conv2D(n_out=64, kernel=(3, 3), activation="relu",
                                convolution_mode="same"), "stem1")
    g.add_layer("stem_pool", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                           convolution_mode="same"), "stem2")
    g.add_layer("stem3", Conv2D(n_out=128, kernel=(3, 3), stride=(2, 2),
                                activation="relu", convolution_mode="same"), "stem_pool")
    x = "stem3"
    for i in range(n_blocks[0]):  # block35 ("A")
        x = _ir_block(g, f"a{i}", x, [[(32, (1, 1))], [(32, (1, 1)), (32, (3, 3))],
                                      [(32, (1, 1)), (32, (3, 3)), (32, (3, 3))]], 128)
    g.add_layer("red_a", Conv2D(n_out=256, kernel=(3, 3), stride=(2, 2),
                                activation="relu", convolution_mode="same"), x)
    x = "red_a"
    for i in range(n_blocks[1]):  # block17 ("B")
        x = _ir_block(g, f"b{i}", x, [[(64, (1, 1))],
                                      [(64, (1, 1)), (64, (1, 7)), (64, (7, 1))]], 256)
    g.add_layer("red_b", Conv2D(n_out=512, kernel=(3, 3), stride=(2, 2),
                                activation="relu", convolution_mode="same"), x)
    x = "red_b"
    for i in range(n_blocks[2]):  # block8 ("C")
        x = _ir_block(g, f"c{i}", x, [[(128, (1, 1))],
                                      [(128, (1, 1)), (128, (1, 3)), (128, (3, 1))]], 512)
    g.add_layer("avgpool", GlobalPooling(pooling="avg"), x)
    g.add_layer("embedding", Dense(n_out=embedding_size, activation="identity"), "avgpool")
    g.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss="mcxent"), "embedding")
    g.set_outputs("out")
    g.updater(updater or {"type": "rmsprop", "lr": 1e-3})
    conf = g.build()
    conf.seed = seed
    conf.dtype = dtype
    return conf


def FaceNetNN4Small2(height: int = 96, width: int = 96, channels: int = 3,
                     num_classes: int = 1001, embedding_size: int = 128,
                     updater=None, seed: int = 12345,
                     dtype: str = "float32") -> ComputationGraphConfiguration:
    """FaceNet NN4-small2 (zoo/model/FaceNetNN4Small2.java): inception-style
    face embedding net (center-loss head in the reference's helper variant —
    use CenterLossOutputLayer via transfer surgery if needed)."""
    g = (ComputationGraphConfiguration.builder()
         .add_inputs("in")
         .set_input_types(InputType.convolutional(height, width, channels)))
    g.add_layer("c1", Conv2D(n_out=64, kernel=(7, 7), stride=(2, 2), activation="relu",
                             convolution_mode="same"), "in")
    g.add_layer("p1", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), "c1")
    g.add_layer("n1", LocalResponseNormalization(), "p1")
    g.add_layer("c2r", Conv2D(n_out=64, kernel=(1, 1), activation="relu",
                              convolution_mode="same"), "n1")
    g.add_layer("c2", Conv2D(n_out=192, kernel=(3, 3), activation="relu",
                             convolution_mode="same"), "c2r")
    g.add_layer("n2", LocalResponseNormalization(), "c2")
    g.add_layer("p2", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), "n2")
    x = _inception(g, "i3a", "p2", 64, 96, 128, 16, 32, 32)
    x = _inception(g, "i3b", x, 64, 96, 128, 32, 64, 64)
    g.add_layer("p3", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), x)
    x = _inception(g, "i4a", "p3", 256, 96, 192, 32, 64, 128)
    x = _inception(g, "i4e", x, 160, 112, 224, 24, 64, 64)
    g.add_layer("p4", Subsampling2D(kernel=(3, 3), stride=(2, 2),
                                    convolution_mode="same"), x)
    x = _inception(g, "i5a", "p4", 256, 96, 384, 32, 128, 128)
    x = _inception(g, "i5b", x, 256, 96, 384, 32, 128, 128)
    g.add_layer("avgpool", GlobalPooling(pooling="avg"), x)
    g.add_layer("embedding", Dense(n_out=embedding_size, activation="identity"), "avgpool")
    g.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss="mcxent"), "embedding")
    g.set_outputs("out")
    g.updater(updater or {"type": "adam", "lr": 1e-3})
    conf = g.build()
    conf.seed = seed
    conf.dtype = dtype
    return conf
