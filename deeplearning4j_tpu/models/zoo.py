"""Zoo architectures (sequential ones; DAG models land with ComputationGraph).

Parity targets (reference deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/model/):
LeNet.java, SimpleCNN.java, TextGenerationLSTM.java here; AlexNet, VGG16/19,
ResNet50, GoogLeNet, Darknet19, TinyYOLO, InceptionResNetV1 arrive as
ComputationGraph configs.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNorm,
    Conv2D,
    Dense,
    DropoutLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    Subsampling2D,
)
from deeplearning4j_tpu.nn.model import MultiLayerConfiguration


def LeNet5(height: int = 28, width: int = 28, channels: int = 1,
           num_classes: int = 10, updater=None, seed: int = 12345,
           dtype: str = "float32") -> MultiLayerConfiguration:
    """LeNet-5 (zoo/model/LeNet.java): conv5x5x20 - pool - conv5x5x50 - pool -
    dense500 - softmax. BASELINE config #1."""
    return MultiLayerConfiguration(
        layers=(
            Conv2D(n_out=20, kernel=(5, 5), stride=(1, 1), activation="identity",
                   convolution_mode="same"),
            Subsampling2D(kernel=(2, 2), stride=(2, 2), pooling="max"),
            Conv2D(n_out=50, kernel=(5, 5), stride=(1, 1), activation="identity",
                   convolution_mode="same"),
            Subsampling2D(kernel=(2, 2), stride=(2, 2), pooling="max"),
            Dense(n_out=500, activation="relu"),
            OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
        ),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "adam", "lr": 1e-3},
        seed=seed,
        dtype=dtype,
    )


def SimpleCNN(height: int = 48, width: int = 48, channels: int = 3,
              num_classes: int = 10, updater=None, seed: int = 12345) -> MultiLayerConfiguration:
    """SimpleCNN.java: small conv stack with BN + dropout."""
    return MultiLayerConfiguration(
        layers=(
            Conv2D(n_out=16, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Conv2D(n_out=16, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Subsampling2D(kernel=(2, 2), stride=(2, 2)),
            Conv2D(n_out=32, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Conv2D(n_out=32, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Subsampling2D(kernel=(2, 2), stride=(2, 2)),
            DropoutLayer(dropout=0.5),
            Dense(n_out=256, activation="relu"),
            OutputLayer(n_out=num_classes, activation="softmax"),
        ),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "adam", "lr": 1e-3},
        seed=seed,
    )


def TextGenerationLSTM(vocab_size: int = 77, timesteps: int = 50,
                       hidden: int = 256, updater=None, seed: int = 12345,
                       dtype: str = "float32") -> MultiLayerConfiguration:
    """TextGenerationLSTM.java / GravesLSTM char-RNN (BASELINE config #3):
    2x LSTM(256) + time-distributed softmax, tBPTT."""
    from deeplearning4j_tpu.nn.layers import GravesLSTM

    return MultiLayerConfiguration(
        layers=(
            GravesLSTM(n_out=hidden),
            GravesLSTM(n_out=hidden),
            RnnOutputLayer(n_out=vocab_size, activation="softmax", loss="mcxent"),
        ),
        input_type=InputType.recurrent(vocab_size, timesteps),
        updater=updater or {"type": "rmsprop", "lr": 1e-3},
        seed=seed,
        backprop_type="tbptt",
        tbptt_fwd_length=50,
        tbptt_back_length=50,
        dtype=dtype,
    )


def TransformerLM(vocab_size: int = 256, max_len: int = 512, d_model: int = 256,
                  n_heads: int = 8, n_blocks: int = 4, ffn_mult: int = 4,
                  sequence_parallel: bool = False, moe_experts: int = 0,
                  updater=None, seed: int = 12345,
                  dtype: str = "bfloat16") -> MultiLayerConfiguration:
    """Decoder-only transformer language model — the framework's flagship.

    Beyond-reference capability (the reference has no attention; its text
    model is the GravesLSTM char-RNN). Designed TPU-first: bf16 by default,
    fused qkv/MLP matmuls on the MXU, optional ring-attention sequence
    parallelism (``sequence_parallel=True`` + a mesh with a ``seq`` axis),
    optional MoE blocks (``moe_experts>0``) whose expert axis shards over the
    mesh's ``model`` axis (expert parallelism).
    """
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingSequence,
        LayerNorm,
        MixtureOfExperts,
        PositionalEmbedding,
        RnnOutputLayer,
        TransformerBlock,
    )

    layers = [
        EmbeddingSequence(n_in=vocab_size, n_out=d_model),
        PositionalEmbedding(max_len=max_len),
    ]
    for i in range(n_blocks):
        layers.append(TransformerBlock(
            n_heads=n_heads, ffn_mult=ffn_mult, causal=True,
            sequence_parallel=sequence_parallel,
        ))
        if moe_experts and i % 2 == 1:  # MoE every second block, switch-style
            layers.append(MixtureOfExperts(n_experts=moe_experts, ffn_mult=ffn_mult))
    layers += [
        LayerNorm(),
        RnnOutputLayer(n_out=vocab_size, activation="softmax", loss="mcxent"),
    ]
    return MultiLayerConfiguration(
        layers=tuple(layers),
        input_type=InputType.recurrent(vocab_size, max_len),
        updater=updater or {"type": "adam", "lr": 3e-4},
        seed=seed,
        dtype=dtype,
    )
