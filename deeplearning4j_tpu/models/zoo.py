"""Zoo architectures (sequential ones; DAG models land with ComputationGraph).

Parity targets (reference deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/model/):
LeNet.java, SimpleCNN.java, TextGenerationLSTM.java here; AlexNet, VGG16/19,
ResNet50, GoogLeNet, Darknet19, TinyYOLO, InceptionResNetV1 arrive as
ComputationGraph configs.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNorm,
    Conv2D,
    Dense,
    DropoutLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    Subsampling2D,
)
from deeplearning4j_tpu.nn.model import MultiLayerConfiguration


def LeNet5(height: int = 28, width: int = 28, channels: int = 1,
           num_classes: int = 10, updater=None, seed: int = 12345,
           dtype: str = "float32") -> MultiLayerConfiguration:
    """LeNet-5 (zoo/model/LeNet.java): conv5x5x20 - pool - conv5x5x50 - pool -
    dense500 - softmax. BASELINE config #1."""
    return MultiLayerConfiguration(
        layers=(
            Conv2D(n_out=20, kernel=(5, 5), stride=(1, 1), activation="identity",
                   convolution_mode="same"),
            Subsampling2D(kernel=(2, 2), stride=(2, 2), pooling="max"),
            Conv2D(n_out=50, kernel=(5, 5), stride=(1, 1), activation="identity",
                   convolution_mode="same"),
            Subsampling2D(kernel=(2, 2), stride=(2, 2), pooling="max"),
            Dense(n_out=500, activation="relu"),
            OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
        ),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "adam", "lr": 1e-3},
        seed=seed,
        dtype=dtype,
    )


def SimpleCNN(height: int = 48, width: int = 48, channels: int = 3,
              num_classes: int = 10, updater=None, seed: int = 12345) -> MultiLayerConfiguration:
    """SimpleCNN.java: small conv stack with BN + dropout."""
    return MultiLayerConfiguration(
        layers=(
            Conv2D(n_out=16, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Conv2D(n_out=16, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Subsampling2D(kernel=(2, 2), stride=(2, 2)),
            Conv2D(n_out=32, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Conv2D(n_out=32, kernel=(3, 3), activation="relu", convolution_mode="same"),
            BatchNorm(),
            Subsampling2D(kernel=(2, 2), stride=(2, 2)),
            DropoutLayer(dropout=0.5),
            Dense(n_out=256, activation="relu"),
            OutputLayer(n_out=num_classes, activation="softmax"),
        ),
        input_type=InputType.convolutional(height, width, channels),
        updater=updater or {"type": "adam", "lr": 1e-3},
        seed=seed,
    )


def TextGenerationLSTM(vocab_size: int = 77, timesteps: int = 50,
                       hidden: int = 256, updater=None, seed: int = 12345,
                       dtype: str = "float32") -> MultiLayerConfiguration:
    """TextGenerationLSTM.java / GravesLSTM char-RNN (BASELINE config #3):
    2x LSTM(256) + time-distributed softmax, tBPTT."""
    from deeplearning4j_tpu.nn.layers import GravesLSTM

    return MultiLayerConfiguration(
        layers=(
            GravesLSTM(n_out=hidden),
            GravesLSTM(n_out=hidden),
            RnnOutputLayer(n_out=vocab_size, activation="softmax", loss="mcxent"),
        ),
        input_type=InputType.recurrent(vocab_size, timesteps),
        updater=updater or {"type": "rmsprop", "lr": 1e-3},
        seed=seed,
        backprop_type="tbptt",
        tbptt_fwd_length=50,
        tbptt_back_length=50,
        dtype=dtype,
    )
