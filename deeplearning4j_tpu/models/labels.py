"""Class-label utilities for zoo models.

Capability parity with the reference's zoo/util/ package: Labels.java:19-27
(getLabel + decodePredictions), BaseLabels.java (text-resource loading),
imagenet/ImageNetLabels.java, darknet/DarknetLabels.java,
darknet/VOCLabels.java.

The reference bundles label lists as classpath resources; this build is
air-gapped, so (matching `models/pretrained.py`) ImageNet/Darknet label
files resolve from ``$DL4J_TPU_HOME/labels/`` or an explicit path. The
20-class VOC list is universal and tiny, so it ships inline.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

ClassPrediction = Tuple[int, str, float]  # (index, label, probability)


def labels_cache_dir() -> str:
    root = os.environ.get("DL4J_TPU_HOME") or os.path.join(
        os.path.expanduser("~"), ".deeplearning4j_tpu")
    return os.path.join(root, "labels")


class BaseLabels:
    """getLabel + decodePredictions over an ordered label list."""

    def __init__(self, labels: Sequence[str]):
        self.labels = list(labels)

    def get_label(self, n: int) -> str:
        return self.labels[n]

    def __len__(self) -> int:
        return len(self.labels)

    def decode_predictions(self, predictions, top: int = 5
                           ) -> List[List[ClassPrediction]]:
        """[batch, classes] probabilities -> per-example top-n
        (index, label, probability), best first (Labels.decodePredictions)."""
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None, :]
        if p.shape[-1] != len(self.labels):
            raise ValueError(
                f"predictions have {p.shape[-1]} classes but {len(self.labels)} "
                "labels are loaded")
        top = min(top, p.shape[-1])
        out: List[List[ClassPrediction]] = []
        for row in p:
            idx = np.argsort(-row)[:top]
            out.append([(int(i), self.labels[int(i)], float(row[int(i)]))
                        for i in idx])
        return out

    @staticmethod
    def _resolve(filename: str, path: Optional[str]) -> str:
        p = path or os.path.join(labels_cache_dir(), filename)
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"Label file not found: {p}. This build is air-gapped — place "
                f"the standard {filename} there (or pass an explicit path).")
        return p

    @classmethod
    def from_text_file(cls, path: str) -> "BaseLabels":
        """One label per line (BaseLabels.getLabels text-resource loader)."""
        with open(path, encoding="utf-8") as f:
            return cls([ln.rstrip("\n") for ln in f if ln.strip() != ""])


class ImageNetLabels(BaseLabels):
    """1000 ImageNet classes (imagenet/ImageNetLabels.java). Loads the
    standard ``imagenet_class_index.json`` ({"0": [wnid, name], ...}) from
    the cache dir or an explicit path."""

    def __init__(self, path: Optional[str] = None):
        p = self._resolve("imagenet_class_index.json", path)
        with open(p, encoding="utf-8") as f:
            idx = json.load(f)
        super().__init__([idx[str(i)][1] for i in range(len(idx))])


class DarknetLabels(BaseLabels):
    """Darknet's ImageNet label list (darknet/DarknetLabels.java):
    ``imagenet.shortnames.list`` (or ``imagenet.labels.list`` with
    short_names=False) from the cache dir."""

    def __init__(self, path: Optional[str] = None, short_names: bool = True):
        name = ("imagenet.shortnames.list" if short_names
                else "imagenet.labels.list")
        p = self._resolve(name, path)
        with open(p, encoding="utf-8") as f:
            super().__init__([ln.rstrip("\n") for ln in f if ln.strip() != ""])


_VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


class VOCLabels(BaseLabels):
    """The 20 PASCAL VOC classes (darknet/VOCLabels.java) — inline, the
    list is a universal constant."""

    def __init__(self):
        super().__init__(_VOC_CLASSES)
