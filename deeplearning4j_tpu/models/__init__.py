"""Model zoo.

Parity: deeplearning4j-zoo (SURVEY.md §2.8) — standard architectures as
config builders. Each returns a configuration whose JSON round-trips, so zoo
models are data, not code.
"""

from deeplearning4j_tpu.models.labels import (
    BaseLabels,
    DarknetLabels,
    ImageNetLabels,
    VOCLabels,
)
from deeplearning4j_tpu.models.pretrained import init_pretrained, pretrained_path
from deeplearning4j_tpu.models.zoo import LeNet5, SimpleCNN, TextGenerationLSTM, TransformerLM
from deeplearning4j_tpu.models.zoo_graph import (
    AlexNet,
    Darknet19,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    ResNet50,
    TinyYOLO,
    VGG16,
    VGG19,
)

__all__ = [
    "LeNet5", "SimpleCNN", "TextGenerationLSTM", "TransformerLM",
    "AlexNet", "VGG16", "VGG19", "ResNet50", "GoogLeNet", "Darknet19",
    "TinyYOLO", "InceptionResNetV1", "FaceNetNN4Small2",
    "init_pretrained", "pretrained_path",
    "BaseLabels", "ImageNetLabels", "DarknetLabels", "VOCLabels",
]
