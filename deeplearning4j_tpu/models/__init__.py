"""Model zoo.

Parity: deeplearning4j-zoo (SURVEY.md §2.8) — standard architectures as
config builders. Each returns a configuration whose JSON round-trips, so zoo
models are data, not code.
"""

from deeplearning4j_tpu.models.zoo import LeNet5, SimpleCNN, TextGenerationLSTM, TransformerLM

__all__ = ["LeNet5", "SimpleCNN", "TextGenerationLSTM", "TransformerLM"]
