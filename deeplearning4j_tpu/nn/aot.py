"""Ahead-of-time compilation and safe executable persistence.

Compile time is the worst production latency the framework has: the first
touch of every (shape bucket, step variant) pays an XLA compile in the
request/step path. This module kills that cold start twice over:

1. **AOT warmup** — walk the shared bucket ladder (``utils/bucketing.py``)
   and eagerly ``jit(...).lower(...).compile()`` every (bucket, variant) the
   step and output paths can hit, BEFORE traffic arrives. ``lower().compile()``
   deliberately does not populate jit's internal dispatch cache, so the
   compiled executables are owned here: :class:`AotFunction` wraps each jitted
   entry point and dispatches through the stored ``Compiled`` on a signature
   match, falling back to the lazy jit otherwise (a miss is never an error).
   The enumeration (``reachable_buckets``) is the same ladder arithmetic the
   retrace guard bounds compiles against, and every warmed bucket is
   cross-registered (``retrace_guard.register_aot_warmed``) so AOT and the
   guard check each other: AOT can't warm shapes the guard would flag, and
   guard violations still fire for traffic outside the warmed set.

2. **Safe executable persistence** — serialized executables
   (``jax.experimental.serialize_executable``) ship in a CRC'd, versioned
   zip bundle written with the same ``serialization._atomic_write_zip``
   durability dance as checkpoints, and ride alongside checkpoints so resume
   restores params AND executables. JAX's own persistent compilation cache
   was root-caused (PR 4, tests/conftest.py) as heap-corrupting on XLA:CPU
   under the pinned jaxlib, so persistence here is gated the μ-cuDNN way —
   measure, then trust: a standalone re-validation harness
   (``python -m deeplearning4j_tpu.nn.aot``) proves
   serialize→deserialize→execute bitwise parity per backend IN A SUBPROCESS
   (a crash there is a failed validation, not a crashed trainer) before any
   bundle is written or read. Default OFF on XLA:CPU; any validation or
   load failure falls back to plain AOT recompile, never crashes.

Trust model: bundle payloads deserialize through jax's pickler. A bundle is
a TRUSTED artifact (same trust class as the code itself), which is why the
manifest pins jax/jaxlib versions, backend platform and the model/ladder
signature, and why every entry is CRC-checked — corruption and version skew
are detected and rejected to the recompile path, but bundles must not be
accepted from untrusted sources (checkpoints stay pickle-free; the bundle
is a separate sidecar precisely so this caveat never touches them).

Env knobs (read per call):

- ``DL4J_TPU_AOT``          master switch for the implicit warmup hooks in
                            ``fit()`` / ``ParallelInference`` (default 0 —
                            explicit ``warm_*`` calls always work)
- ``DL4J_TPU_AOT_BUNDLE``   executable persistence: ``0`` off, ``1`` on
                            (still validation-gated), ``auto`` (default) =
                            on for non-CPU backends that pass validation
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import subprocess
import sys
import threading
import time
import zipfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.analysis import retrace_guard
from deeplearning4j_tpu.obs import profile as _profile
from deeplearning4j_tpu.utils import bucketing

__all__ = [
    "AotFunction",
    "BUNDLE_FORMAT_VERSION",
    "bundle_path_for",
    "distributed_bundle_manifest",
    "distributed_bundle_path",
    "enabled",
    "model_signature",
    "persistence_allowed",
    "reachable_buckets",
    "restore_bundle",
    "restore_distributed_bundle",
    "save_bundle",
    "save_distributed_bundle",
    "toolchain_fingerprint",
    "validate_persistence",
    "warm_dp",
    "warm_fit",
    "warm_serving",
    "warm_serving_bundled",
    "wrap",
]

BUNDLE_FORMAT_VERSION = 2
_MANIFEST_ENTRY = "manifest.json"


def enabled() -> bool:
    """Master switch for the implicit warmup hooks (fit/ParallelInference).
    Default OFF: a full ladder walk is a deliberate cost, and the test
    suite must not pay it on every model construction."""
    return os.environ.get("DL4J_TPU_AOT", "0") == "1"


# ---------------------------------------------------------------------------
# Signature keys
# ---------------------------------------------------------------------------


def _leaf_meta(leaf) -> Tuple:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        shape = np.shape(leaf)
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        dtype = np.asarray(leaf).dtype
    return (tuple(shape), np.dtype(dtype).str,
            bool(getattr(leaf, "weak_type", False)))


def signature_key(args: tuple, kwargs: dict) -> Tuple:
    """Hashable call signature: the (args, kwargs) pytree structure plus
    per-leaf (shape, dtype, weak_type) — exactly what decides whether jit
    would dispatch to an existing executable or retrace."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_meta(l) for l in leaves))


def _sig_label(key: Tuple) -> str:
    """Stable short label for a signature key (cost-model gauge label when
    no bucket is known)."""
    return f"sig{abs(hash(key)) % 10**8:08d}"


# ---------------------------------------------------------------------------
# The dispatcher
# ---------------------------------------------------------------------------


class _RestoredStaticCall:
    """Call adapter for bundle-restored executables of static-arg sites:
    strips the static positions from the full-signature dispatch call.
    ``raw_compiled`` stays reachable so re-bundling serializes the real
    executable, not this wrapper."""

    __slots__ = ("raw_compiled", "_statics")

    def __init__(self, compiled, statics):
        self.raw_compiled = compiled
        self._statics = frozenset(statics)

    def __call__(self, *args, **kwargs):
        dyn = tuple(a for i, a in enumerate(args) if i not in self._statics)
        return self.raw_compiled(*dyn, **kwargs)


class AotFunction:
    """A jitted function plus a cache of AOT-compiled executables.

    ``lower().compile()`` does NOT warm jit's internal dispatch cache, so
    ahead-of-time compiles must own dispatch: calls whose signature matches
    a warmed entry go straight to the stored ``Compiled`` (donation
    semantics identical — the executable was lowered from the same jit);
    everything else falls through to the lazy jit. The fast path for
    un-warmed functions is a single truthiness check on an empty dict."""

    def __init__(self, jitted, site: str,
                 static_argnums: Optional[Tuple[int, ...]] = None):
        self._jit = jitted
        self.site = site
        self._static_argnums = tuple(static_argnums or ())
        self._compiled: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()

    def _key(self, args: tuple, kwargs: dict) -> Tuple:
        """Dispatch key. ``signature_key`` sees only shape/dtype, under
        which all python-int static args collide (k=1 and k=16 both read as
        a 0-d int leaf) — but jit keys statics by VALUE, so the AOT cache
        must too or warming k=1 silently shadows every other k."""
        key = signature_key(args, kwargs)
        if self._static_argnums:
            key = key + (tuple(args[i] for i in self._static_argnums
                               if i < len(args)),)
        return key

    # -- warmup ------------------------------------------------------------
    def warm(self, *args, cost_key: Optional[str] = None, **kwargs):
        """Compile (without executing) for this exact call signature and
        cache the executable; returns the ``Compiled`` (idempotent).
        ``cost_key`` labels the executable's cost-model gauges (warmers pass
        the bucket, e.g. ``b64``; defaults to a signature hash)."""
        key = self._key(args, kwargs)
        existing = self._compiled.get(key)
        if existing is not None:
            return existing
        with obs.compile_span(self.site, mode="aot"):
            compiled = self._jit.lower(*args, **kwargs).compile()
        _profile.harvest_compiled(
            self.site, compiled, key=cost_key or _sig_label(key))
        with self._lock:
            # a concurrent warm of the same key wastes one compile at worst
            self._compiled.setdefault(key, compiled)
        return self._compiled[key]

    def install(self, key: Tuple, compiled) -> None:
        """Adopt an already-built executable (bundle restore path)."""
        raw = compiled
        if self._static_argnums:
            # a deserialized executable takes DYNAMIC args only (the
            # serialized in_tree drops static_argnums), while a fresh
            # lower().compile() object takes the full signature — adapt so
            # dispatch stays uniform
            compiled = _RestoredStaticCall(raw, self._static_argnums)
        with self._lock:
            self._compiled[key] = compiled
        _profile.harvest_compiled(self.site, raw, key=_sig_label(key))

    @property
    def compiled_count(self) -> int:
        return len(self._compiled)

    def signatures(self) -> List[Tuple]:
        return list(self._compiled)

    # -- dispatch ----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if self._compiled:
            key = self._key(args, kwargs)
            compiled = self._compiled.get(key)
            if compiled is not None:
                try:
                    out = compiled(*args, **kwargs)
                except TypeError:
                    # aval/layout mismatch the key was too coarse to see:
                    # raised before execution, so inputs (incl. donated
                    # buffers) are intact — evict and recompile lazily
                    with self._lock:
                        self._compiled.pop(key, None)
                    obs.counter(
                        "dl4j_aot_dispatch_fallbacks_total",
                        "AOT executables evicted on dispatch mismatch",
                        ("site",)).inc(site=self.site)
                    return self._lazy(args, kwargs)
                obs.counter(
                    "dl4j_aot_warm_hits_total",
                    "dispatches served by an AOT/bundle-restored executable",
                    ("site",)).inc(site=self.site)
                return out
        return self._lazy(args, kwargs)

    def _lazy(self, args, kwargs):
        out = self._jit(*args, **kwargs)
        # a compile just happened on this dispatch iff record_trace flagged
        # the site during tracing; capture its abstract signature so
        # cost_report() can price the executable later. One set lookup on
        # the warm path, aval capture only on the (rare) compile path.
        if _profile.wants_exemplar(self.site):
            _profile.note_exemplar(self.site, self, args, kwargs)
        return out

    # convenience parity with jax.jit objects used elsewhere
    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)


def wrap(jitted, site: str, model=None,
         static_argnums: Optional[Tuple[int, ...]] = None) -> AotFunction:
    """Wrap a jitted entry point for AOT dispatch and register it on the
    model's AOT function registry (``model._aot_fns``). Executables restored
    from a bundle before the function existed (``restore_bundle`` on a fresh
    model) are waiting in ``model._aot_pending`` and are adopted here.
    ``static_argnums`` must mirror the jit's own, so dispatch keys carry the
    static VALUES exactly like jit's cache does."""
    fn = AotFunction(jitted, site, static_argnums=static_argnums)
    if model is not None:
        reg = model.__dict__.setdefault("_aot_fns", {})
        reg[site] = fn
        pending = model.__dict__.get("_aot_pending")
        if pending:
            for key, compiled in pending.pop(site, ()):
                fn.install(key, compiled)
    return fn


def clear_sites(model, sites) -> None:
    """Drop registry entries for re-built jitted functions (stale
    executables must not be re-bundled after e.g. an updater change)."""
    reg = model.__dict__.get("_aot_fns")
    if reg:
        for s in sites:
            reg.pop(s, None)


# ---------------------------------------------------------------------------
# Ladder enumeration
# ---------------------------------------------------------------------------


def reachable_buckets(max_n: int,
                      ladder: Optional[bucketing.BucketLadder] = None) -> List[int]:
    """Every bucket a leading dim in [1, max_n] can land on — the exact set
    the retrace guard's predicted-compile bound counts, walked bucket
    boundary by bucket boundary (O(#buckets), not O(max_n))."""
    ladder = ladder or bucketing.ladder_from_env()
    out: List[int] = []
    n = 1
    while n <= max_n:
        b = ladder.bucket(n)
        out.append(b)
        n = b + 1
    return out


# ---------------------------------------------------------------------------
# Warmers
# ---------------------------------------------------------------------------


def _is_graph(model) -> bool:
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    return isinstance(model, ComputationGraph)


def _dummy_features(model, batch: int):
    from deeplearning4j_tpu.nn.memory import _dummy_for

    if _is_graph(model):
        return tuple(_dummy_for(model.conf.input_types[n], batch, model.dtype)
                     for n in model.conf.inputs)
    return _dummy_for(model.conf.input_type, batch, model.dtype)


def warm_serving(model, max_batch: int,
                 ladder: Optional[bucketing.BucketLadder] = None) -> int:
    """AOT-compile the inference path for every ladder bucket reachable by
    batches up to ``max_batch`` (the ParallelInference coalescing cap /
    server warm target). Returns the number of executables now warm."""
    if model.params is None:
        model.init()
    is_graph = _is_graph(model)
    if is_graph and model._has_batch_vertices:
        # Stack/Unstack graphs run unbucketed (output() skips padding), so
        # there is no finite bucket set to enumerate
        obs.event("aot_warmup_skipped", site="cg.output",
                  reason="batch_vertices")
        return 0
    buckets = (reachable_buckets(max_batch, ladder)
               if bucketing.bucketing_enabled() else [max_batch])
    fn = model._get_output_fn()
    site = "cg.output" if is_graph else "mln.output"
    t0 = time.perf_counter()
    for b in buckets:
        feats = _dummy_features(model, b)
        if is_graph:
            fn.warm(model.params, model.state, model._input_dict(feats), None,
                    cost_key=f"b{b}")
        else:
            fn.warm(model.params, model.state, feats, None, cost_key=f"b{b}")
    retrace_guard.register_aot_warmed(site, buckets)
    obs.event("aot_warmup", site=site, buckets=list(buckets),
              executables=fn.compiled_count,
              duration_s=round(time.perf_counter() - t0, 6))
    return fn.compiled_count


def warm_serving_bundled(model, max_batch: int, bundle_path,
                         ladder: Optional[bucketing.BucketLadder] = None
                         ) -> Tuple[int, int]:
    """The serving tier's one-call warm pipeline: restore any executables
    persisted at ``bundle_path``, ladder-warm the inference path up to
    ``max_batch`` (restored signatures dispatch instead of recompiling),
    then persist the now-warm set back (best-effort; both bundle directions
    are validation-gated by ``persistence_allowed``). Returns
    ``(restored, warmed)`` executable counts."""
    restored = restore_bundle(model, bundle_path) if bundle_path else 0
    warmed = warm_serving(model, max_batch, ladder)
    if bundle_path and warmed:
        save_bundle(model, bundle_path)
    return restored, warmed


def _first_fit_batch(model, data, batch_size):
    """(x, y, fm, lm, pad_target) for the first batch fit() will dispatch,
    or None when the source is streaming (not inspectable without consuming
    it) — mirrors fit()'s own _fit_pad_target/_iter_batches handling."""
    from deeplearning4j_tpu.nn import model as M

    source = data() if callable(data) else data
    if hasattr(source, "as_tuple"):
        source = source.as_tuple()
    if not (isinstance(source, (tuple, list)) and len(source) >= 2
            and not isinstance(source[0], (tuple, list, dict))):
        return None
    pad_target = (M._fit_pad_target(source, batch_size)
                  if bucketing.bucketing_enabled() else None)
    x, y, fm, lm = M._as_batch(source)
    b = min(batch_size or len(x), len(x))
    sl = slice(0, b)
    return (x[sl], y[sl] if y is not None else None,
            fm[sl] if fm is not None else None,
            lm[sl] if lm is not None else None, pad_target)


def warm_fit(model, data, batch_size: Optional[int] = None) -> int:
    """AOT-compile the training step for the batch shape(s) fit() is about
    to dispatch — uses the REAL leading arrays (label dtypes matter: sparse
    integer labels trace a different executable than dense floats), sliced,
    never consumed. Streaming sources return 0 (their shapes aren't
    knowable up front). With a bundle already restored this is a pure
    cache-key check: zero compiles, and the first step is warm."""
    from deeplearning4j_tpu.nn.model import _cast_input, _cast_labels

    import jax
    import jax.numpy as jnp

    if _is_graph(model):
        return _warm_fit_graph(model, data, batch_size)
    if model.params is None:
        model.init()
    first = _first_fit_batch(model, data, batch_size)
    if first is None:
        return 0
    x, y, fm, lm, pad_target = first
    ew = None
    if pad_target is not None:
        # the padded-fit calling convention: uniform lm/ew channels so full
        # and partial batches share one executable (bucketing.pad_fit_batch)
        x, y, fm, lm, ew = bucketing.pad_fit_batch(
            x, y, fm, lm, pad_target, site="mln.fit")
    step = model._get_step_fn(False)
    before = step.compiled_count
    t0 = time.perf_counter()
    bucket = pad_target if pad_target is not None else len(x)
    step.warm(
        model.params, model.opt_state, model.state,
        jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
        _cast_input(x, model.dtype), _cast_labels(y, model.dtype),
        jnp.asarray(fm, model.dtype) if fm is not None else None,
        jnp.asarray(lm, model.dtype) if lm is not None else None, (),
        ex_weight=jnp.asarray(ew, model.dtype) if ew is not None else None,
        cost_key=f"b{bucket}",
    )
    retrace_guard.register_aot_warmed("mln.step", [bucket])
    obs.event("aot_warmup", site="mln.step", buckets=[int(bucket)],
              executables=step.compiled_count,
              duration_s=round(time.perf_counter() - t0, 6))
    return step.compiled_count - before


def _warm_fit_graph(model, data, batch_size: Optional[int]) -> int:
    import jax
    import jax.numpy as jnp

    if model.params is None:
        model.init()
    source = data() if callable(data) else data
    if hasattr(source, "as_tuple"):
        source = source.as_tuple()
    if not model._is_single_multibatch(source):
        return 0
    pad_target = (model._fit_pad_target_multi(source, batch_size)
                  if bucketing.bucketing_enabled() else None)
    # _as_multi_batch normalizes/casts exactly as _iter_multi does for the
    # real epoch stream; fit_batch then passes the members verbatim, so no
    # second cast here either
    f, l, fm, lm = model._as_multi_batch(source)
    b = min(batch_size or len(f[0]), len(f[0]))
    sl_t = lambda t: (tuple(a[:b] if a is not None else None for a in t)
                      if t is not None else None)
    f, l, fm, lm = sl_t(f), sl_t(l), sl_t(fm), sl_t(lm)
    ew = None
    if pad_target is not None:
        f, l, fm, lm, ew = bucketing.pad_fit_multi(
            f, l, fm, lm, pad_target, site="cg.fit")
    step = model._get_step_fn(False)
    before = step.compiled_count
    t0 = time.perf_counter()
    bucket = pad_target if pad_target is not None else b
    step.warm(
        model.params, model.opt_state, model.state,
        jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
        model._input_dict(f), l, model._mask_dict(fm), lm, {},
        ex_weight=jnp.asarray(ew, model.dtype) if ew is not None else None,
        cost_key=f"b{bucket}",
    )
    retrace_guard.register_aot_warmed("cg.step", [bucket])
    obs.event("aot_warmup", site="cg.step", buckets=[int(bucket)],
              executables=step.compiled_count,
              duration_s=round(time.perf_counter() - t0, 6))
    return step.compiled_count - before


def warm_dp(runner, x, y, fm=None, lm=None, ew=None) -> int:
    """AOT-compile a DataParallelStep's shard_map step for one global batch
    shape (the grad-exchange variant of the tentpole: compressed and/or
    sharded-update executables are a different trace than the single-chip
    step). Enters the exchange layout if needed — ``lower`` only reads
    avals, so the donated carry is untouched."""
    from deeplearning4j_tpu.nn.model import _cast_input, _cast_labels

    import jax
    import jax.numpy as jnp

    if not runner._active:
        runner.begin()
    model = runner.model
    step = runner._step
    before = step.compiled_count
    t0 = time.perf_counter()
    bucket = len(x[0] if runner.is_graph else x)
    if runner.is_graph:
        f = tuple(_cast_input(a, model.dtype) for a in x)
        step.warm(
            model.params, (runner._opt_flat, runner._residual), model.state,
            jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
            model._input_dict(f), y, model._mask_dict(fm), lm, {},
            jnp.asarray(ew, model.dtype) if ew is not None else None,
            cost_key=f"b{bucket}")
        site = "cg.step"
    else:
        step.warm(
            model.params, (runner._opt_flat, runner._residual), model.state,
            jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
            _cast_input(x, model.dtype), _cast_labels(y, model.dtype),
            jnp.asarray(fm, model.dtype) if fm is not None else None,
            jnp.asarray(lm, model.dtype) if lm is not None else None, (),
            jnp.asarray(ew, model.dtype) if ew is not None else None,
            cost_key=f"b{bucket}")
        site = "mln.step"
    retrace_guard.register_aot_warmed(site, [bucket])
    obs.event("aot_warmup", site="dp.step", buckets=[int(bucket)],
              executables=step.compiled_count,
              duration_s=round(time.perf_counter() - t0, 6))
    return step.compiled_count - before


# ---------------------------------------------------------------------------
# Persistence gating: the re-validation harness
# ---------------------------------------------------------------------------


_validated: Dict[str, bool] = {}
_validated_lock = threading.Lock()


def reset_validation() -> None:
    with _validated_lock:
        _validated.clear()


def _selftest() -> dict:
    """The standalone re-validation harness body: compile, serialize,
    deserialize, execute original and restored executables on identical
    inputs, compare BITWISE. Run in a subprocess by ``validate_persistence``
    so a jaxlib that corrupts on deserialization (the PR 4 XLA:CPU failure
    class) crashes the probe, not the trainer."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import serialize_executable as jse

    out = {"backend": jax.default_backend(), "ok": False, "cases": []}

    def case(shape, donate):
        def f(w, x):
            return jnp.tanh(x @ w) * 0.5 + x.sum()

        # probe executable, not a training step — exempt from the
        # one-step-program rule
        jitted = jax.jit(  # graftlint: disable=step-wiring
            f, donate_argnums=(0,) if donate else ())
        mk = lambda: (
            jnp.asarray(np.linspace(-1.0, 1.0, shape[1] * shape[1],
                                    dtype=np.float32).reshape(shape[1],
                                                              shape[1])),
            jnp.asarray(np.arange(shape[0] * shape[1],
                                  dtype=np.float32).reshape(shape)),
        )
        compiled = jitted.lower(*mk()).compile()
        payload, in_tree, out_tree = jse.serialize(compiled)
        restored = jse.deserialize_and_load(payload, in_tree, out_tree)
        # validation harness, not a hot path: the whole point is comparing
        # materialized bytes on the host
        a = np.asarray(compiled(*mk()))  # graftlint: disable=host-sync
        b = np.asarray(restored(*mk()))  # graftlint: disable=host-sync
        return {"shape": list(shape), "donate": donate,
                "parity": bool(  # graftlint: disable=host-sync
                    a.tobytes() == b.tobytes()),
                "payload_bytes": len(payload)}

    for shape, donate in (((4, 8), True), ((16, 8), False)):
        out["cases"].append(case(shape, donate))
    out["ok"] = all(c["parity"] for c in out["cases"])
    return out


def validate_persistence(backend: Optional[str] = None,
                         timeout_s: float = 120.0) -> bool:
    """Run the re-validation harness for ``backend`` in a subprocess (once
    per process; cached). ANY failure — parity mismatch, nonzero exit,
    segfault, timeout (e.g. a TPU whose single-process tunnel the parent
    already holds) — disables persistence for that backend; the system
    then falls back to plain AOT recompilation."""
    import jax

    backend = backend or jax.default_backend()
    with _validated_lock:
        if backend in _validated:
            return _validated[backend]
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = backend
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    ok = False
    detail: Any = None
    try:
        with obs.compile_span("aot.validate", backend=backend):
            proc = subprocess.run(
                [sys.executable, "-m", "deeplearning4j_tpu.nn.aot"],
                cwd=repo_root, env=env, capture_output=True,
                timeout=timeout_s)
        if proc.returncode == 0:
            detail = json.loads(proc.stdout.decode().strip().splitlines()[-1])
            ok = bool(detail.get("ok"))
        else:
            detail = {"returncode": proc.returncode,
                      "stderr": proc.stderr.decode(errors="replace")[-500:]}
    except Exception as e:  # timeout, spawn failure, garbled output
        detail = {"error": repr(e)}
    with _validated_lock:
        _validated[backend] = ok
    obs.event("aot_validation", backend=backend, ok=ok, detail=detail)
    return ok


def persistence_allowed(backend: Optional[str] = None) -> bool:
    """Whether executable bundles may be written/read on this backend:
    ``DL4J_TPU_AOT_BUNDLE=0`` never, ``=1`` if validation passes, ``auto``
    (default) only on non-CPU backends that pass validation — XLA:CPU under
    the pinned jaxlib earned its default-off (PR 4 heap corruption)."""
    mode = os.environ.get("DL4J_TPU_AOT_BUNDLE", "auto")
    if mode == "0":
        return False
    import jax

    backend = backend or jax.default_backend()
    if mode != "1" and backend == "cpu":
        return False
    return validate_persistence(backend)


# ---------------------------------------------------------------------------
# Bundles
# ---------------------------------------------------------------------------


def model_signature(model) -> str:
    """Identity of the model the bundle's executables were compiled for:
    config JSON + class + dtype. A restored bundle whose signature differs
    would hand avals-mismatched executables to the dispatcher, so the
    manifest check rejects it up front."""
    conf = json.loads(model.conf.to_json())
    # the init seed shapes parameter VALUES, not compiled computations; a
    # resume into a differently-seeded fresh model must accept the bundle
    conf.pop("seed", None)
    h = hashlib.sha256()
    h.update(type(model).__name__.encode())
    h.update(str(model.dtype).encode())
    h.update(json.dumps(conf, sort_keys=True).encode())
    return h.hexdigest()


def bundle_path_for(checkpoint_path) -> str:
    """Sidecar path for the executable bundle shipped with a checkpoint.
    A distinct suffix keeps it out of the checkpoint index's globs (it is
    a cache, not state — losing it costs a recompile, nothing else)."""
    return os.fspath(checkpoint_path) + ".aotbundle"


def toolchain_fingerprint() -> dict:
    """The (jax, jaxlib, backend) triple that decides whether a persisted
    artifact — executable bundle or tuning-DB entry — can still be trusted.
    Shared by the bundle manifest and ``deeplearning4j_tpu.tune``: a knob
    choice measured on one toolchain is as stale as a serialized executable
    compiled on it."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = "unknown"
    return {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
        "backend": jax.default_backend(),
    }


def _manifest(model, entries) -> dict:
    return {
        "format_version": BUNDLE_FORMAT_VERSION,
        **toolchain_fingerprint(),
        "model_signature": None if model is None else model_signature(model),
        "entries": entries,
    }


def save_bundle(model, path) -> Optional[dict]:
    """Serialize every AOT-compiled executable on ``model`` into a CRC'd,
    versioned zip bundle (atomic write). Returns ``{"path", "entries",
    "bytes"}`` or None when persistence is gated off / nothing is warm.
    Never raises: a checkpoint must not fail over its executable sidecar."""
    from jax.experimental import serialize_executable as jse

    from deeplearning4j_tpu.utils import serialization

    try:
        if not persistence_allowed():
            return None
        reg = model.__dict__.get("_aot_fns") or {}
        entries = []
        blobs: List[bytes] = []
        for site, fn in sorted(reg.items()):
            for key in fn.signatures():
                compiled = fn._compiled.get(key)
                if compiled is None:
                    continue
                try:
                    payload, in_tree, out_tree = jse.serialize(
                        getattr(compiled, "raw_compiled", compiled))
                except Exception:
                    # backend refuses to serialize this executable: skip it,
                    # the rest of the bundle is still worth shipping
                    obs.event("aot_bundle_entry_skipped", site=site)
                    continue
                blob = pickle.dumps({
                    "site": site, "key": key, "payload": payload,
                    "in_tree": in_tree, "out_tree": out_tree,
                }, protocol=pickle.HIGHEST_PROTOCOL)
                name = f"exec/{len(blobs):04d}.pkl"
                entries.append({"name": name, "site": site,
                                "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
                                "size": len(blob)})
                blobs.append(blob)
        if not blobs:
            return None
        manifest = _manifest(model, entries)

        def write_entries(zf):
            zf.writestr(_MANIFEST_ENTRY, json.dumps(manifest, indent=2))
            for meta, blob in zip(entries, blobs):
                zf.writestr(meta["name"], blob)

        serialization._atomic_write_zip(path, write_entries)
        total = sum(len(b) for b in blobs)
        obs.counter("dl4j_aot_bundle_saved_total",
                    "executable bundles written").inc()
        obs.event("aot_bundle_saved", path=str(path), entries=len(blobs),
                  bytes=total, backend=manifest["backend"])
        return {"path": str(path), "entries": len(blobs), "bytes": total}
    except Exception as e:
        obs.event("aot_bundle_save_failed", path=str(path), error=repr(e))
        return None


def _reject(path, reason: str, **fields) -> int:
    obs.counter("dl4j_aot_bundle_rejected_total",
                "executable bundles rejected (corrupt, version or backend "
                "mismatch) — the system recompiled instead", ("reason",)
                ).inc(reason=reason)
    obs.event("aot_bundle_rejected", path=str(path), reason=reason, **fields)
    return 0


def restore_bundle(model, path) -> int:
    """Load a bundle's executables into ``model``'s AOT dispatchers.
    Validation-gated like writes; manifest version/backend/signature skew,
    per-entry CRC failures and deserialization errors all reject to the
    recompile path (counter + event, no exception). Returns the number of
    executables installed. Sites whose jitted function does not exist yet
    (fresh model, DataParallelStep not built) park in ``model._aot_pending``
    and are adopted by ``wrap`` when the function is created."""
    import jax
    from jax.experimental import serialize_executable as jse

    try:
        if not os.path.exists(path):
            return 0
        if not persistence_allowed():
            return _reject(path, "persistence_disabled")
        with zipfile.ZipFile(path) as zf:
            manifest = json.loads(zf.read(_MANIFEST_ENTRY))
            if manifest.get("format_version") != BUNDLE_FORMAT_VERSION:
                return _reject(path, "format_version",
                               found=manifest.get("format_version"))
            import jaxlib

            jaxlib_version = getattr(jaxlib, "__version__", "unknown")
            if (manifest.get("jax_version") != jax.__version__
                    or manifest.get("jaxlib_version") != jaxlib_version):
                return _reject(
                    path, "version_mismatch",
                    bundle_jax=manifest.get("jax_version"),
                    bundle_jaxlib=manifest.get("jaxlib_version"))
            if manifest.get("backend") != jax.default_backend():
                return _reject(path, "backend_mismatch",
                               bundle_backend=manifest.get("backend"),
                               backend=jax.default_backend())
            sig = model_signature(model)
            if manifest.get("model_signature") != sig:
                return _reject(path, "model_signature")
            installed = 0
            pending = model.__dict__.setdefault("_aot_pending", {})
            reg = model.__dict__.setdefault("_aot_fns", {})
            for meta in manifest.get("entries", []):
                blob = zf.read(meta["name"])
                if (zlib.crc32(blob) & 0xFFFFFFFF) != meta.get("crc32"):
                    return _reject(path, "crc_mismatch", entry=meta["name"])
                rec = pickle.loads(blob)
                with obs.compile_span(rec["site"], mode="bundle_restore"):
                    compiled = jse.deserialize_and_load(
                        rec["payload"], rec["in_tree"], rec["out_tree"])
                fn = reg.get(rec["site"])
                if fn is not None:
                    fn.install(rec["key"], compiled)
                else:
                    pending.setdefault(rec["site"], []).append(
                        (rec["key"], compiled))
                installed += 1
        # materialize the standard step/output dispatchers now so parked
        # executables attach immediately (cheap: jit wrapping, no trace)
        _attach_standard_fns(model)
        obs.counter("dl4j_aot_bundle_restored_total",
                    "executable bundles restored").inc()
        obs.event("aot_bundle_restored", path=str(path), entries=installed)
        return installed
    except Exception as e:
        return _reject(path, "load_error", error=repr(e))


def _attach_standard_fns(model) -> None:
    pending = model.__dict__.get("_aot_pending") or {}
    prefix = "cg" if _is_graph(model) else "mln"
    if f"{prefix}.step" in pending:
        model._get_step_fn(False)
    if f"{prefix}.step.tbptt" in pending:
        model._get_step_fn(True)
    if f"{prefix}.output" in pending:
        model._get_output_fn()


# ---------------------------------------------------------------------------
# Distributed bundles (elastic multi-host checkpoint layout)
# ---------------------------------------------------------------------------


def distributed_bundle_path(base, rank: int) -> str:
    """Per-host executable-bundle shard path under the elastic checkpoint
    layout: ``<base>_r<rank>.aotbundle``."""
    return f"{os.fspath(base)}_r{int(rank)}.aotbundle"


def _distributed_sidecar(base, rank: int) -> str:
    return f"{os.fspath(base)}_r{int(rank)}.aotmanifest.json"


def save_distributed_bundle(model, base, rank: int) -> Optional[dict]:
    """Write this host's executable-bundle shard plus a CRC'd sidecar
    manifest entry. Bundles hold compiled executables for the REPLICATED
    model program — identical across data-parallel ranks — so any rank's
    shard can warm any other rank (the straggler-serving property the
    distributed restore exploits). Gated and non-raising like
    :func:`save_bundle`; returns its info dict or None."""
    path = distributed_bundle_path(base, rank)
    info = save_bundle(model, path)
    if info is None:
        return None
    try:
        entry = {
            "rank": int(rank),
            "file": os.path.basename(path),
            "crc32": _file_crc32(path),
            "size": os.path.getsize(path),
            "model_signature": model_signature(model),
            **toolchain_fingerprint(),
        }
        tmp = f"{_distributed_sidecar(base, rank)}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(entry, f, indent=1)
        os.replace(tmp, _distributed_sidecar(base, rank))
        info["manifest"] = entry
    except Exception as e:
        obs.event("aot_bundle_save_failed", path=str(path), error=repr(e))
    return info


def _file_crc32(path) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(1 << 20)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def distributed_bundle_manifest(base) -> Dict[int, dict]:
    """Merge the per-rank sidecar manifests for ``base`` into
    ``{rank: entry}``; unreadable sidecars are dropped (their bundles will
    fail CRC anyway)."""
    import glob as _glob

    out: Dict[int, dict] = {}
    for p in sorted(_glob.glob(f"{os.fspath(base)}_r*.aotmanifest.json")):
        try:
            with open(p, "r") as f:
                entry = json.load(f)
            out[int(entry["rank"])] = entry
        except (OSError, ValueError, KeyError):
            continue
    return out


def restore_distributed_bundle(model, base, rank: int) -> int:
    """Restore executables from the distributed bundle layout: this rank's
    own shard first, then — because the executables are rank-agnostic — ANY
    other rank's CRC-valid shard (a rejoining straggler whose own shard is
    lost or corrupt warms itself from a survivor's). Returns executables
    installed; 0 on nothing usable (the recompile path, never raises)."""
    manifest = distributed_bundle_manifest(base)
    order = [rank] + sorted(t for t in manifest if t != rank)
    for t in order:
        path = distributed_bundle_path(base, t)
        if not os.path.exists(path):
            continue
        entry = manifest.get(t)
        if entry is not None:
            try:
                if (_file_crc32(path) != entry.get("crc32")
                        or os.path.getsize(path) != entry.get("size")):
                    _reject(path, "crc_mismatch", rank=t)
                    continue
            except OSError:
                continue
        n = restore_bundle(model, path)
        if n > 0:
            if t != rank:
                obs.event("aot_bundle_served_by_peer", rank=rank,
                          served_by=t, path=str(path))
            return n
    return 0


# ---------------------------------------------------------------------------
# Harness entry point: python -m deeplearning4j_tpu.nn.aot
# ---------------------------------------------------------------------------


def _main() -> int:
    result = _selftest()
    sys.stdout.write(json.dumps(result) + "\n")
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(_main())
