"""Layer-config base classes, registry, and JSON serde.

This is the TPU-native replacement for the reference's config DSL
(/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/NeuralNetConfiguration.java:82
and the ~45 classes under nn/conf/layers/): frozen dataclasses that
round-trip to JSON with polymorphic ``@type`` tags (the reference uses
Jackson subtype registration, NeuralNetConfiguration.java:405-430).

Config IS the API: a model is a list/DAG of these configs; ``init`` builds a
params pytree, ``apply`` is a pure traced function. There are no stateful
layer objects and no per-layer ``backpropGradient`` — autodiff of the whole
step replaces the reference's hand-written backward passes.

Layer contract
--------------
- ``output_type(input_type) -> InputType``      config-time shape inference
- ``init(key, input_type, dtype) -> params``    parameter pytree (dict)
- ``init_state(input_type) -> state``           non-trainable state (e.g. BN
                                                running stats); {} if none
- ``apply(params, state, x, *, train, rng, mask) -> (y, new_state)``
- ``propagate_mask(mask, input_type) -> mask``  mask flow (default identity)
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, initializers
from deeplearning4j_tpu.nn.initializers import Distribution
from deeplearning4j_tpu.nn.input_type import InputType

layer_registry: Dict[str, type] = {}


def register_layer(type_name: str):
    """Class decorator registering a layer config under a stable JSON tag."""

    def deco(cls):
        cls._type_name = type_name
        layer_registry[type_name] = cls
        return cls

    return deco


def _encode_value(v):
    if isinstance(v, Distribution):
        return {"@distribution": v.to_dict()}
    if isinstance(v, InputType):
        return {"@input_type": v.to_dict()}
    if isinstance(v, LayerConfig):
        return v.to_dict()
    if isinstance(v, (tuple, list)):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    if callable(v):
        # Custom activation/init functions can't round-trip; store a marker.
        return {"@callable": getattr(v, "__name__", "lambda")}
    return v


def _decode_value(v):
    if isinstance(v, dict):
        if "@distribution" in v:
            return Distribution.from_dict(v["@distribution"])
        if "@input_type" in v:
            return InputType.from_dict(v["@input_type"])
        if "@type" in v:
            return layer_from_dict(v)
        if "@callable" in v:
            raise ValueError(
                f"Config contained a non-serializable callable '{v['@callable']}'; "
                "it cannot be restored from JSON."
            )
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        # JSON arrays come back as lists; configs store shape-like fields as
        # tuples (kernel, stride, padding, shape) — normalize so a config
        # round-trips to an EQUAL dataclass.
        return tuple(_decode_value(x) for x in v)
    return v


def yaml_dump(d: dict) -> str:
    """The ONE yaml encoding used by every config class (layer, MLN, CG) —
    keep dialect choices (sort_keys) in one place."""
    import yaml

    return yaml.safe_dump(d, sort_keys=False)


def yaml_load(s: str) -> dict:
    import yaml

    return yaml.safe_load(s)


def layer_from_dict(d: dict) -> "LayerConfig":
    tag = d.get("@type")
    if tag not in layer_registry:
        raise ValueError(f"Unknown layer type '{tag}'. Known: {sorted(layer_registry)}")
    cls = layer_registry[tag]
    kwargs = {k: _decode_value(v) for k, v in d.items() if k != "@type"}
    # Dataclass fields may evolve across versions: ignore unknown keys so old
    # JSON keeps loading (the reference's regression-test contract, SURVEY §4).
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(kwargs) - names
    for k in unknown:
        kwargs.pop(k)
    cfg = cls(**kwargs)
    return cfg


@dataclass
class LayerConfig:
    """Base for all layer configs.

    Mirrors the knobs on the reference's BaseLayer conf (activation, weight
    init, l1/l2, per-layer updater override, dropout, name) — see
    nn/conf/layers/BaseLayer.java in the reference.
    """

    name: Optional[str] = None
    dropout: float = 0.0            # input dropout, DL4J semantics (keep-prob = 1-dropout)
    l1: float = 0.0
    l2: float = 0.0
    updater: Optional[dict] = None  # per-layer updater override (see training/updaters.py)
    trainable: bool = True          # False == FrozenLayer wrapper in the reference
    # parameter constraints applied post-update inside the jitted step
    # (nn/conf/constraint/ parity; see nn/constraints.py for spec format)
    constraints: Any = ()
    # per-layer gradient normalization (BaseLayer.gradientNormalization /
    # gradientNormalizationThreshold parity — see
    # train/updaters.apply_gradient_normalization for the mode names)
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    # train-time weight noise (nn/conf/weightnoise/ parity):
    #   {"type": "dropconnect", "p": 0.95}  p = weight RETAIN probability,
    #       inverted scaling (DropConnect.java applies DropOutInverted)
    #   {"type": "gaussian", "stddev": 0.01, "additive": true}
    weight_noise: Optional[dict] = None

    def uses_rng(self) -> bool:
        """Does a TRAIN-mode apply() draw randomness? Layers with extra
        noise sources (GaussianNoise/GaussianDropout, attention dropout)
        extend this; wrapper layers are covered generically via their
        ``rnn`` attribute. Drives the chained-fit auto gate
        (MultiLayerNetwork._chain_k): only rng-free models chain by
        default, so the per-step rng stream is never silently changed."""
        inner = getattr(self, "rnn", None)
        return (bool(self.dropout) or self.weight_noise is not None
                or (inner is not None and inner.uses_rng()))

    # -- registry / serde --------------------------------------------------
    _type_name = "base"

    def to_dict(self) -> dict:
        d = {"@type": self._type_name}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = _encode_value(v)
        return d

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    def to_yaml(self) -> str:
        """YAML serde (reference NeuralNetConfiguration.toYaml:376 twin)."""
        return yaml_dump(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "LayerConfig":
        return layer_from_dict(d)

    @staticmethod
    def from_json(s: str) -> "LayerConfig":
        return layer_from_dict(json.loads(s))

    @staticmethod
    def from_yaml(s: str) -> "LayerConfig":
        return layer_from_dict(yaml_load(s))

    # -- shape/param contract ---------------------------------------------
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key: jax.Array, input_type: InputType, dtype=jnp.float32) -> Dict[str, jax.Array]:
        return {}

    def init_state(self, input_type: InputType) -> Dict[str, jax.Array]:
        return {}

    def apply(
        self,
        params: Dict[str, jax.Array],
        state: Dict[str, jax.Array],
        x: jax.Array,
        *,
        train: bool = False,
        rng: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    def propagate_mask(self, mask, input_type: InputType):
        return mask

    # -- helpers -----------------------------------------------------------
    def nested_param_layers(self) -> dict:
        """Sub-layer configs owning nested param-dict subtrees, keyed by the
        subtree name (e.g. TransformerBlock's 'attn' params belong to its
        MultiHeadAttention). TP sharding rules resolve nested params through
        this hook rather than guessing from subtree names."""
        return {}

    def activation_fn(self):
        return activations.get(getattr(self, "activation", "identity"))

    def maybe_dropout_input(self, x, train: bool, rng):
        """Input dropout as configured on the layer (inverted dropout)."""
        if not train or self.dropout <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"Layer {self.name or self._type_name}: dropout requires an rng key")
        keep = 1.0 - self.dropout
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    def maybe_weight_noise(self, params, train: bool, rng):
        """Perturb weight-class params at train time per ``weight_noise``
        (DropConnect.java / WeightNoise.java). Pure function of (params,
        rng): fused into the jitted step, identity at inference."""
        wn = self.weight_noise
        if not wn or not train or not params:
            return params
        if rng is None:
            raise ValueError(f"Layer {self.name or self._type_name}: weight noise requires an rng key")
        kind = wn.get("type", "dropconnect")
        bias_names = self.BIAS_PARAM_NAMES

        def visit(p, key):
            out = {}
            for i, (name, v) in enumerate(sorted(p.items())):
                k = jax.random.fold_in(key, i)
                if isinstance(v, dict):
                    out[name] = visit(v, k)
                    continue
                if name in bias_names and not wn.get("apply_to_bias", False):
                    out[name] = v
                    continue
                if kind == "dropconnect":
                    keep = float(wn.get("p", 0.5))
                    mask = jax.random.bernoulli(k, keep, v.shape)
                    out[name] = jnp.where(mask, v / keep, 0.0)
                elif kind == "gaussian":
                    noise = float(wn.get("stddev", 0.01)) * jax.random.normal(k, v.shape, v.dtype)
                    out[name] = v + noise if wn.get("additive", True) else v * (1.0 + noise)
                else:
                    raise ValueError(f"unknown weight_noise type {kind!r}")
            return out

        return visit(params, rng)

    # Param names treated as bias-class (excluded from l1/l2 by default, as in
    # the reference where regularization applies to weight-class params only;
    # cf. DefaultParamInitializer BIAS_KEY / BatchNormalizationParamInitializer).
    BIAS_PARAM_NAMES = frozenset({"b", "vb", "bias", "beta", "gamma"})

    def regularization_penalty(self, params: Dict[str, jax.Array]) -> jax.Array:
        """L1/L2 penalty over this layer's weight-class params. Recurses into
        nested param dicts (wrapper layers like Bidirectional)."""
        pen = jnp.asarray(0.0, jnp.float32)
        if self.l1 == 0.0 and self.l2 == 0.0:
            return pen

        def visit(p):
            nonlocal pen
            for pname, v in p.items():
                if isinstance(v, dict):
                    visit(v)
                    continue
                if pname in self.BIAS_PARAM_NAMES:
                    continue
                if self.l1:
                    pen = pen + self.l1 * jnp.sum(jnp.abs(v))
                if self.l2:
                    pen = pen + 0.5 * self.l2 * jnp.sum(v * v)

        visit(params)
        return pen

    def has_params(self, input_type: InputType) -> bool:
        key = jax.random.PRNGKey(0)
        return bool(self.init(key, input_type))


@dataclass
class FeedForwardLayerConfig(LayerConfig):
    """Base for layers with n_in/n_out + activation + weight init."""

    n_in: Optional[int] = None     # inferred from the previous layer when None
    n_out: int = 0
    activation: Any = "identity"
    weight_init: Any = "xavier"
    bias_init: float = 0.0

    def with_n_in(self, n_in: int):
        if self.n_in is None:
            return dataclasses.replace(self, n_in=int(n_in))
        return self

    def infer_n_in(self, input_type: InputType) -> int:
        """What this layer's n_in means given an input type (flat features by
        default; conv layers override to channels)."""
        return input_type.flat_size()
