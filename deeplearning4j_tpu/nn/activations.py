"""Activation functions.

Capability parity with the reference's ND4J ``IActivation`` set (consumed by
every layer config via ``activation="relu"`` etc., see e.g.
/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/layers/BaseLayer
usage). On TPU an activation is just a traced elementwise function — XLA
fuses it into the surrounding matmul/conv, so there is no IActivation object
hierarchy, only a name → function registry (names kept DL4J-compatible,
lowercase).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Activation = Callable[[jax.Array], jax.Array]

_REGISTRY: Dict[str, Activation] = {}


def register(name: str) -> Callable[[Activation], Activation]:
    def deco(fn: Activation) -> Activation:
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


def get(name_or_fn) -> Activation:
    """Resolve an activation by DL4J-style name (case-insensitive) or pass
    through a callable (the SameDiff-style custom-activation escape hatch)."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"Unknown activation '{name_or_fn}'. Known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[key]


def names() -> list:
    return sorted(_REGISTRY)


@register("identity")
def identity(x):
    return x


_REGISTRY["linear"] = identity


@register("relu")
def relu(x):
    return jax.nn.relu(x)


@register("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@register("leakyrelu")
def leakyrelu(x):
    return jax.nn.leaky_relu(x, negative_slope=0.01)


@register("elu")
def elu(x):
    return jax.nn.elu(x)


@register("selu")
def selu(x):
    return jax.nn.selu(x)


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("hardsigmoid")
def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@register("tanh")
def tanh(x):
    return jnp.tanh(x)


@register("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@register("rationaltanh")
def rationaltanh(x):
    # 1.7159 * tanh(2x/3) rational approximation used by DL4J's
    # ActivationRationalTanh.
    a = jnp.abs(2.0 * x / 3.0)
    approx = 1.0 - 1.0 / (1.0 + a + a * a + 1.41645 * a**4)
    return 1.7159 * jnp.sign(x) * approx


@register("rectifiedtanh")
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@register("softmax")
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register("logsoftmax")
def logsoftmax(x):
    return jax.nn.log_softmax(x, axis=-1)


@register("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register("cube")
def cube(x):
    return x * x * x


@register("swish")
def swish(x):
    return jax.nn.silu(x)


@register("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@register("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register("thresholdedrelu")
def thresholdedrelu(x):
    return jnp.where(x > 1.0, x, 0.0)
