"""Neural-network core: configs, activations, initializers, losses, layers.

TPU-native analogue of the reference's ``deeplearning4j-nn`` module
(/root/reference/deeplearning4j-nn, SURVEY.md §2.1): the config DSL is kept
(dataclasses + JSON round-trip), but forward/backward become pure JAX
functions differentiated by autodiff instead of hand-written
``backpropGradient`` methods.
"""
