"""Transfer learning: surgery on trained networks.

Parity: nn/transferlearning/TransferLearning.java:32 (Builder:34,
setFeatureExtractor:84, nOutReplace:98-143, GraphBuilder),
FineTuneConfiguration.java, TransferLearningHelper.java.

TPU-first mechanics: "freeze" is ``trainable=False`` on a layer config —
the build assigns that layer a no-op updater, and because the whole step is
one jitted function XLA dead-code-eliminates the frozen layers' gradient
computation entirely (the reference instead wraps layers in FrozenLayer
objects that skip applyUpdater at runtime). Param transfer is by
shape-matched copy into a freshly-built model.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.graph import (
    ComputationGraph,
    ComputationGraphConfiguration,
    VertexSpec,
)
from deeplearning4j_tpu.nn.model import MultiLayerConfiguration, MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Global overrides applied to every layer during surgery
    (FineTuneConfiguration.java). Only non-None fields are applied."""

    updater: Any = None
    seed: Optional[int] = None
    dropout: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None

    def apply_to_layer(self, layer):
        kw = {}
        if self.dropout is not None and hasattr(layer, "dropout"):
            kw["dropout"] = self.dropout
        if self.l1 is not None and hasattr(layer, "l1"):
            kw["l1"] = self.l1
        if self.l2 is not None and hasattr(layer, "l2"):
            kw["l2"] = self.l2
        return dataclasses.replace(layer, **kw) if kw else layer


def _tree_shapes_match(a, b) -> bool:
    la, sa = jax.tree_util.tree_flatten(a)
    lb, sb = jax.tree_util.tree_flatten(b)
    if sa != sb or len(la) != len(lb):
        return False
    return all(x.shape == y.shape and x.dtype == y.dtype for x, y in zip(la, lb))


class TransferLearning:
    """Entry point: ``TransferLearning.builder(mln)`` or
    ``TransferLearning.graph_builder(cg)``."""

    @staticmethod
    def builder(model: MultiLayerNetwork) -> "TransferLearningBuilder":
        return TransferLearningBuilder(model)

    @staticmethod
    def graph_builder(model: ComputationGraph) -> "TransferLearningGraphBuilder":
        return TransferLearningGraphBuilder(model)


class TransferLearningBuilder:
    """Sequential-model surgery (TransferLearning.Builder). Layer indices
    refer to the USER config (conf.layers), not the resolved stack."""

    def __init__(self, model: MultiLayerNetwork):
        if model.params is None:
            raise ValueError("Transfer learning needs an initialized model")
        self._model = model
        self._layers: List[Any] = list(model.conf.layers)
        self._ftc: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._reinit: set = set()  # indices whose params must NOT transfer

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._ftc = ftc
        return self

    def set_feature_extractor(self, layer_idx: int):
        """Freeze layers 0..layer_idx inclusive (setFeatureExtractor:84)."""
        self._freeze_until = layer_idx
        return self

    def n_out_replace(self, layer_idx: int, n_out: int, weight_init: Any = None):
        """Change a layer's n_out; its params and the NEXT layer's (n_in
        changes) are re-initialized (nOutReplace:98-143)."""
        layer = self._layers[layer_idx]
        kw: Dict[str, Any] = {"n_out": n_out}
        if weight_init is not None:
            kw["weight_init"] = weight_init
        self._layers[layer_idx] = dataclasses.replace(layer, **kw)
        # The replaced layer (and its successor, whose n_in depends on it) is
        # ALWAYS re-initialized, even when the new n_out equals the old one —
        # reference nOutReplace semantics.
        self._reinit.add(layer_idx)
        if layer_idx + 1 < len(self._layers) and hasattr(self._layers[layer_idx + 1], "n_in"):
            # clear explicit n_in so it re-infers from the new n_out
            self._layers[layer_idx + 1] = dataclasses.replace(
                self._layers[layer_idx + 1], n_in=None
            )
            self._reinit.add(layer_idx + 1)
        return self

    def remove_output_layer(self):
        self._layers.pop()
        return self

    def remove_layers_from_output(self, n: int):
        del self._layers[len(self._layers) - n :]
        return self

    def add_layer(self, layer):
        self._layers.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        layers = list(self._layers)
        if self._freeze_until is not None:
            for i in range(min(self._freeze_until + 1, len(layers))):
                layers[i] = dataclasses.replace(layers[i], trainable=False)
        if self._ftc is not None:
            layers = [self._ftc.apply_to_layer(l) for l in layers]
        conf_kw = dict(
            layers=tuple(layers),
            input_type=self._model.conf.input_type,
            seed=self._model.conf.seed if not (self._ftc and self._ftc.seed is not None)
            else self._ftc.seed,
            updater=self._ftc.updater if (self._ftc and self._ftc.updater is not None)
            else self._model.conf.updater,
            dtype=self._model.conf.dtype,
            backprop_type=self._model.conf.backprop_type,
            tbptt_fwd_length=self._model.conf.tbptt_fwd_length,
            tbptt_back_length=self._model.conf.tbptt_back_length,
        )
        new = MultiLayerNetwork(MultiLayerConfiguration(**conf_kw)).init()
        # resolved indices of layers marked for re-initialization (config
        # indices shift when auto-preprocessors are interleaved; preprocessor
        # type tags are "pp_*")
        no_transfer = set()
        cfg_i = 0
        for r, l in enumerate(new.layers):
            if l._type_name.startswith("pp_"):
                continue
            if cfg_i in self._reinit:
                no_transfer.add(r)
            cfg_i += 1
        # shape-matched positional param transfer over the resolved stacks
        for i in range(min(len(new.params), len(self._model.params))):
            if i in no_transfer:
                continue
            if _tree_shapes_match(new.params[i], self._model.params[i]):
                new.params = new.params[:i] + (
                    jax.tree_util.tree_map(jnp.copy, self._model.params[i]),
                ) + new.params[i + 1 :]
                new.state = new.state[:i] + (
                    jax.tree_util.tree_map(jnp.copy, self._model.state[i]),
                ) + new.state[i + 1 :]
        return new


class TransferLearningGraphBuilder:
    """DAG surgery (TransferLearning.GraphBuilder): vertices addressed by
    name; params transfer by name + shape match."""

    def __init__(self, model: ComputationGraph):
        if model.params is None:
            raise ValueError("Transfer learning needs an initialized model")
        self._model = model
        conf = model.conf
        self._vertices: Dict[str, VertexSpec] = dict(conf.vertices)
        self._outputs = list(conf.outputs)
        self._ftc: Optional[FineTuneConfiguration] = None
        self._frozen: set = set()

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._ftc = ftc
        return self

    def set_feature_extractor(self, *vertex_names: str):
        """Freeze the named vertices and everything upstream of them."""
        conf = self._model.conf
        frontier = list(vertex_names)
        while frontier:
            v = frontier.pop()
            if v in self._frozen or v not in self._vertices:
                continue
            self._frozen.add(v)
            frontier.extend(self._vertices[v].inputs)
        return self

    def n_out_replace(self, name: str, n_out: int, weight_init: Any = None):
        spec = self._vertices[name]
        kw: Dict[str, Any] = {"n_out": n_out}
        if weight_init is not None:
            kw["weight_init"] = weight_init
        self._vertices[name] = VertexSpec(
            dataclasses.replace(spec.config, **kw), spec.inputs
        )
        # clear explicit n_in on direct consumers so they re-infer
        for vname, vspec in list(self._vertices.items()):
            if name in vspec.inputs and hasattr(vspec.config, "n_in") \
                    and vspec.config.n_in is not None:
                self._vertices[vname] = VertexSpec(
                    dataclasses.replace(vspec.config, n_in=None), vspec.inputs
                )
        return self

    def remove_vertex(self, name: str, and_outputs: bool = False):
        self._vertices.pop(name)
        if and_outputs and name in self._outputs:
            self._outputs.remove(name)
        return self

    def add_layer(self, name: str, layer, *inputs: str):
        self._vertices[name] = VertexSpec(layer, tuple(inputs))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        self._vertices[name] = VertexSpec(vertex, tuple(inputs))
        return self

    def set_outputs(self, *names: str):
        self._outputs = list(names)
        return self

    def build(self) -> ComputationGraph:
        vertices: Dict[str, VertexSpec] = {}
        for name, spec in self._vertices.items():
            cfg = spec.config
            if name in self._frozen and any(
                    f.name == "trainable" for f in dataclasses.fields(cfg)):
                # param-free vertices (merge/elementwise/...) carry trainable
                # only as a class attribute and have nothing to freeze
                cfg = dataclasses.replace(cfg, trainable=False)
            if self._ftc is not None and hasattr(cfg, "dropout"):
                cfg = self._ftc.apply_to_layer(cfg)
            vertices[name] = VertexSpec(cfg, spec.inputs)
        old = self._model.conf
        conf = ComputationGraphConfiguration(
            inputs=old.inputs,
            input_types=old.input_types,
            vertices=vertices,
            outputs=tuple(self._outputs),
            seed=old.seed,
            updater=self._ftc.updater if (self._ftc and self._ftc.updater is not None)
            else old.updater,
            dtype=old.dtype,
        )
        new = ComputationGraph(conf).init()
        for name in new.params:
            if name in self._model.params and _tree_shapes_match(
                new.params[name], self._model.params[name]
            ):
                new.params[name] = jax.tree_util.tree_map(
                    jnp.copy, self._model.params[name]
                )
                new.state[name] = jax.tree_util.tree_map(
                    jnp.copy, self._model.state[name]
                )
        return new


class TransferLearningHelper:
    """Featurize-once training of the unfrozen tail
    (TransferLearningHelper.java): run the frozen front once per dataset,
    then iterate only the small unfrozen sub-network."""

    def __init__(self, model: MultiLayerNetwork, frozen_till: int):
        """``frozen_till``: last frozen USER layer index (inclusive)."""
        if model.params is None:
            raise ValueError("needs an initialized model")
        self._model = model
        # map user layer index -> resolved index (auto-inserted preprocessors
        # shift it; they are registered under "pp_*" type names)
        resolved_idx = -1
        user_idx = -1
        for i, l in enumerate(model.layers):
            if not l._type_name.startswith("pp_"):
                user_idx += 1
            if user_idx == frozen_till:
                resolved_idx = i
                break
        if resolved_idx < 0:
            raise ValueError(f"frozen_till={frozen_till} out of range")
        self._boundary = resolved_idx + 1
        sub_layers = tuple(model.layers[self._boundary :])
        sub_conf = MultiLayerConfiguration(
            layers=sub_layers,
            input_type=model.layer_input_types[self._boundary]
            if self._boundary < len(model.layers) else model.output_type,
            seed=model.conf.seed,
            updater=model.conf.updater,
            dtype=model.conf.dtype,
        )
        self._sub = MultiLayerNetwork(sub_conf).init()
        self._sub.params = tuple(
            jax.tree_util.tree_map(jnp.copy, p) for p in model.params[self._boundary :]
        )
        self._sub.state = tuple(
            jax.tree_util.tree_map(jnp.copy, s) for s in model.state[self._boundary :]
        )

    @property
    def unfrozen_network(self) -> MultiLayerNetwork:
        return self._sub

    def featurize(self, batch):
        """(x, y, ...) -> (features_at_boundary, y, ...)."""
        from deeplearning4j_tpu.nn.model import _as_batch, _cast_input

        x, y, fm, lm = _as_batch(batch)
        a, _, _, mask, _ = self._model._forward(
            self._model.params, self._model.state, _cast_input(x, self._model.dtype),
            train=False, rngs=None,
            fmask=jnp.asarray(fm, self._model.dtype) if fm is not None else None,
            upto=self._boundary,
        )
        return (np.asarray(a), y, np.asarray(mask) if mask is not None else None, lm)

    def fit_featurized(self, featurized, epochs: int = 1, batch_size=None):
        self._sub.fit(featurized, epochs=epochs, batch_size=batch_size)
        # write trained tail params back into the full model
        n = len(self._model.params)
        self._model.params = self._model.params[: self._boundary] + tuple(
            jax.tree_util.tree_map(jnp.copy, p) for p in self._sub.params
        )
        self._model.state = self._model.state[: self._boundary] + tuple(
            jax.tree_util.tree_map(jnp.copy, s) for s in self._sub.state
        )
        assert len(self._model.params) == n
        return self._sub

    def output_from_featurized(self, features):
        return self._sub.output(features)
