"""Sequential model: MultiLayerConfiguration + MultiLayerNetwork.

Capability parity with the reference's
nn/multilayer/MultiLayerNetwork.java (3,538 LoC: init:548, feedForward:878,
fit:1261, output:2005, computeGradientAndScore:2353) and
nn/conf/MultiLayerConfiguration.java — re-designed TPU-first:

- The whole training iteration (forward, loss, autodiff backward, gradient
  normalization, updater, parameter update) is ONE pure function traced and
  compiled ONCE by XLA, with params/opt-state donated so updates happen
  in-place in HBM. The reference instead drives ~1 JNI kernel dispatch per op
  per layer per iteration (SURVEY.md §3.1).
- Parameters are a pytree (tuple of per-layer dicts), not a flattened view;
  optimizer state lives in a parallel pytree (no UpdaterBlocks).
- Backward comes from jax.grad of the step — the per-layer
  ``backpropGradient`` methods of the reference do not exist.
- Truncated BPTT (MultiLayerNetwork.doTruncatedBPTT:1514) is scan-over-chunks
  with carried RNN state; ``rnn_time_step`` keeps carries on device between
  calls (rnnTimeStep:2371 equivalents).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.analysis import retrace_guard
from deeplearning4j_tpu.nn import aot
from deeplearning4j_tpu.nn.config import LayerConfig, layer_from_dict, _encode_value
from deeplearning4j_tpu.utils import bucketing
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrent
from deeplearning4j_tpu.nn.preprocessors import infer_preprocessor
from deeplearning4j_tpu.train.updaters import (
    apply_gradient_normalization,
    make_updater,
    normalize_updater,
    scale_lr,
)


@dataclass
class MultiLayerConfiguration:
    """Sequential-network config (MultiLayerConfiguration.java parity).

    ``updater`` is the network default; a layer's ``updater`` field overrides
    it (DL4J per-layer updater semantics). JSON round-trip via
    to_json/from_json is the long-lived artifact contract (§5.6).
    """

    layers: Tuple[LayerConfig, ...] = ()
    input_type: Optional[InputType] = None
    seed: int = 12345
    updater: Any = "sgd"
    dtype: str = "float32"
    backprop_type: str = "standard"        # "standard" | "tbptt"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    # OptimizationAlgorithm dispatch (optimize/Solver.java:50-80):
    # "stochastic_gradient_descent" (the jitted step) or one of the
    # deterministic solvers in train/solvers.py
    optimization_algo: str = "stochastic_gradient_descent"
    solver_iterations: int = 5             # solver steps per batch (non-SGD)

    def __post_init__(self):
        self.layers = tuple(self.layers)

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu/MultiLayerConfiguration",
            "version": 1,
            "layers": [l.to_dict() for l in self.layers],
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "seed": self.seed,
            "updater": _encode_value(self.updater),
            "dtype": self.dtype,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "optimization_algo": self.optimization_algo,
            "solver_iterations": self.solver_iterations,
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=tuple(layer_from_dict(ld) for ld in d["layers"]),
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            seed=d.get("seed", 12345),
            updater=d.get("updater", "sgd"),
            dtype=d.get("dtype", "float32"),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            optimization_algo=d.get("optimization_algo", "stochastic_gradient_descent"),
            solver_iterations=d.get("solver_iterations", 5),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """YAML twin of to_json (MultiLayerConfiguration.toYaml parity)."""
        from deeplearning4j_tpu.nn.config import yaml_dump

        return yaml_dump(self.to_dict())

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        from deeplearning4j_tpu.nn.config import yaml_load

        return MultiLayerConfiguration.from_dict(yaml_load(s))


def _cast_input(x, dtype):
    """Cast a feature array to the model dtype, PRESERVING (a) integer/bool
    dtypes (token ids must not round-trip through bf16 — ids >256 would
    corrupt) and (b) float64 arrays (the x64 gradient-check path drives the
    model at double precision on purpose)."""
    if x is None:
        return None
    x = jnp.asarray(x)
    if (
        jnp.issubdtype(x.dtype, jnp.integer)
        or x.dtype == jnp.bool_
        or x.dtype == jnp.float64
    ):
        return x
    return x.astype(dtype)


def _as_batch(batch):
    """Normalize a batch to (features, labels, features_mask, labels_mask).

    Accepts (x, y), (x, y, fmask), (x, y, fmask, lmask) tuples, a dict with
    those keys, or a DataSet object — the DataSet surface of the reference.
    """
    if hasattr(batch, "as_tuple"):  # datasets.DataSet / MultiDataSet
        batch = batch.as_tuple()
    if isinstance(batch, dict):
        return (
            batch["features"],
            batch.get("labels"),
            batch.get("features_mask"),
            batch.get("labels_mask"),
        )
    if isinstance(batch, (tuple, list)):
        x = batch[0]
        y = batch[1] if len(batch) > 1 else None
        fm = batch[2] if len(batch) > 2 else None
        lm = batch[3] if len(batch) > 3 else None
        return x, y, fm, lm
    return batch, None, None, None


# The shared micro-batching policy (chained dispatch, grad-accumulation
# scan) and the compiled-step wiring now live in nn/step_program.py — the
# single step-program module (ISSUE 13). The underscore aliases keep the
# historical import surface (nn.graph, parallel/, tests) intact.
from deeplearning4j_tpu.nn.step_program import (  # noqa: F401,E402
    CHAIN_AUTO_PARAM_LIMIT,
    StepProgram,
    accum_applicable as _accum_applicable,
    accum_value_and_grad as _accum_value_and_grad,
    chain_k_from_env as _chain_k_from_env,
    grad_accum_from_env as _grad_accum_from_env,
)


def _sig_dtype(a):
    # prefer the dtype attribute: np.asarray on a device array would pull
    # it back to host just to read metadata (hurts the prefetched-fit path)
    dt = getattr(a, "dtype", None)
    return np.dtype(dt if dt is not None else np.asarray(a).dtype).str


def _batch_sig(arrays) -> tuple:
    """Shape+dtype signature used to decide whether two batches may share
    one chained dispatch (same-shape different-dtype batches must NOT be
    stacked: jnp.stack would silently dtype-promote, e.g. routing sparse
    integer labels through the dense-loss path)."""
    return tuple((np.shape(a), _sig_dtype(a))
                 for a in arrays if a is not None)


def _cast_labels(y, dtype):
    """Model-dtype cast that PRESERVES integer (sparse) class labels — the
    loss head's sparse path needs the integer dtype intact."""
    if y is None:
        return None
    y = jnp.asarray(y)
    return y if jnp.issubdtype(y.dtype, jnp.integer) else y.astype(dtype)


def _iter_batches(data, batch_size=None):
    """Yield batches from (x, y[, masks]) arrays (optionally minibatched), a
    DataSet object, or any iterable of batches."""
    if hasattr(data, "as_tuple"):  # datasets.DataSet: unpack, then minibatch
        data = data.as_tuple()
    if isinstance(data, (tuple, list)) and len(data) >= 2 and not isinstance(data[0], (tuple, list, dict)):
        x, y, fm, lm = _as_batch(data)
        n = len(x)
        if batch_size is None or batch_size >= n:
            yield (x, y, fm, lm)
            return
        for i in range(0, n, batch_size):  # final partial batch included
            sl = slice(i, min(i + batch_size, n))
            yield (
                x[sl],
                y[sl] if y is not None else None,
                fm[sl] if fm is not None else None,
                lm[sl] if lm is not None else None,
            )
        return
    for b in data:
        yield _as_batch(b)


def _fit_pad_target(source, batch_size) -> Optional[int]:
    """Uniform per-batch row count for a fit() over in-memory arrays, or None.

    When minibatching arrays whose length is not a multiple of batch_size,
    the final partial batch would otherwise trace a SECOND training
    executable just for its odd shape. Returns batch_size in that case so
    every batch — including the tail, padded with zero example-weights — runs
    through one executable. Streaming iterables return None: their batch
    shapes aren't knowable up front, and padding only the surprise tail
    would still cost the extra ew/lmask trace it tries to avoid."""
    if batch_size is None:
        return None
    if hasattr(source, "as_tuple"):
        source = source.as_tuple()
    if (isinstance(source, (tuple, list)) and len(source) >= 2
            and not isinstance(source[0], (tuple, list, dict))):
        n = len(source[0])
        if n > batch_size and n % batch_size != 0:
            return batch_size
    return None


def _device_prefetch_enabled() -> bool:
    import os as _os

    return _os.environ.get("DL4J_TPU_DEVICE_PREFETCH", "1") != "0"


class MultiLayerNetwork:
    """Stateful model facade over pure jitted functions.

    Mutable host state: ``params``, ``state`` (BN running stats etc.),
    ``opt_state``, ``iteration``. The jitted step itself is pure; this class
    is the ergonomic shell matching the reference's MultiLayerNetwork API
    (init/fit/output/score/evaluate/rnnTimeStep).
    """

    def __init__(self, conf: MultiLayerConfiguration):
        if conf.input_type is None:
            raise ValueError("MultiLayerConfiguration.input_type is required")
        self.conf = conf
        self.dtype = jnp.dtype(conf.dtype)
        self._resolve_layers()
        self.params = None
        self.state = None
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.batch_in_epoch = 0
        self._rng = jax.random.PRNGKey(conf.seed)
        self._step_fn = None
        self._tbptt_step_fn = None
        self._output_fn = None
        self._rnn_carries: Optional[list] = None
        self.listeners: list = []
        self.divergence_guard = None
        self._lr_scale = 1.0
        self._pending_residuals = None

    # -- resolution: preprocessors + n_in inference + per-layer input types --
    def _resolve_layers(self):
        layers: List[LayerConfig] = []
        input_types: List[InputType] = []
        it = self.conf.input_type
        for layer in self.conf.layers:
            pre = infer_preprocessor(it, layer)
            if pre is not None:
                layers.append(pre)
                input_types.append(it)
                it = pre.output_type(it)
            if hasattr(layer, "with_n_in"):
                layer = layer.with_n_in(layer.infer_n_in(it))
            layers.append(layer)
            input_types.append(it)
            it = layer.output_type(it)
        self.layers: List[LayerConfig] = layers
        self.layer_input_types: List[InputType] = input_types
        self.output_type: InputType = it
        self._carry_flags = [
            isinstance(l, BaseRecurrent) and getattr(l, "SUPPORTS_CARRY", False) for l in layers
        ]
        out = self.layers[-1]
        self._has_loss_head = hasattr(out, "score")

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    # -- init --------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        key = jax.random.PRNGKey(self.conf.seed if seed is None else seed)
        keys = jax.random.split(key, len(self.layers))
        self.params = tuple(
            l.init(k, it, self.dtype) for l, k, it in zip(self.layers, keys, self.layer_input_types)
        )
        self.state = tuple(l.init_state(it) for l, it in zip(self.layers, self.layer_input_types))
        self._build_updaters()
        self.opt_state = tuple(u.init(p) for u, p in zip(self._updaters, self.params))
        self.iteration = 0
        self.epoch = 0
        return self

    def _build_updaters(self):
        # _lr_scale is the divergence-guard rollback backoff (resilience.py);
        # 1.0 outside rollback, so this is normalize_updater by default
        scale = float(getattr(self, "_lr_scale", 1.0))
        default = scale_lr(self.conf.updater, scale)
        self._updaters = []
        for l in self.layers:
            if not getattr(l, "trainable", True):
                self._updaters.append(make_updater("noop"))
            elif getattr(l, "updater", None) is not None:
                self._updaters.append(make_updater(scale_lr(l.updater, scale)))
            else:
                self._updaters.append(make_updater(default))

    def _clear_compiled(self):
        """Drop compiled step closures (updaters or divergence-guard config
        changed — both are baked into the trace). AOT-warmed step
        executables are stale for the same reason; the output path is
        untouched (inference doesn't trace updaters or guards)."""
        self._step_fn = None
        self._tbptt_step_fn = None
        self._chain_step_fn = None
        self._solver = None
        aot.clear_sites(self, ("mln.step", "mln.step.tbptt"))

    def set_divergence_guard(self, guard) -> "MultiLayerNetwork":
        """Install a train/resilience.DivergenceGuard (None to remove).
        Clears compiled step caches: the skip_batch policy's select is traced
        into the step executable."""
        self.divergence_guard = guard
        self._clear_compiled()
        runner = getattr(self, "_dp_runner", None)
        if runner is not None:
            runner.rebuild_step()
        return self

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))

    # -- forward -----------------------------------------------------------
    def _forward(self, params, state, x, *, train, rngs, fmask=None, carries=None,
                 upto: Optional[int] = None, collect=False, ex_weight=None,
                 deterministic=False):
        """Walk the layer stack. Returns (act, new_state, new_carries, mask,
        activations_list). ``ex_weight`` is a per-example [B] validity weight
        consumed only by layers that declare CONSUMES_EXAMPLE_WEIGHT
        (BatchNorm excludes zero-weighted padding rows from batch stats).
        ``deterministic`` (score(train=True) path): layers whose train-mode
        apply draws randomness (dropout / weight noise — ``uses_rng``) run in
        eval mode while everything else keeps train-mode semantics, so
        normalization layers still use batch statistics but the result is a
        pure function of (params, state, x)."""
        n = len(self.layers) if upto is None else upto
        acts_list = []
        new_state = list(state)
        new_carries = list(carries) if carries is not None else None
        mask = fmask
        a = _cast_input(x, self.dtype)
        for i in range(n):
            layer = self.layers[i]
            lrng = rngs[i] if rngs is not None else None
            ltrain = train and not (deterministic and layer.uses_rng())
            p_i = params[i]
            if ltrain and layer.weight_noise and lrng is not None:
                # separate stream from input dropout on the same layer
                p_i = layer.maybe_weight_noise(p_i, ltrain, jax.random.fold_in(lrng, 0x5EED))
            if new_carries is not None and self._carry_flags[i]:
                a2 = layer.maybe_dropout_input(a, ltrain, lrng)
                a, c = layer.apply_seq(p_i, a2, new_carries[i], mask)
                new_carries[i] = c
                ns = state[i]
            elif ex_weight is not None and getattr(layer, "CONSUMES_EXAMPLE_WEIGHT", False):
                a, ns = layer.apply(p_i, state[i], a, train=ltrain, rng=lrng,
                                    mask=mask, ex_weight=ex_weight)
            else:
                a, ns = layer.apply(p_i, state[i], a, train=ltrain, rng=lrng, mask=mask)
            new_state[i] = ns
            mask = layer.propagate_mask(mask, self.layer_input_types[i])
            if collect:
                acts_list.append(a)
        return a, tuple(new_state), (tuple(new_carries) if new_carries is not None else None), mask, acts_list

    def _layer_rngs(self, rng):
        return list(jax.random.split(rng, len(self.layers)))

    def feed_forward(self, x, train: bool = False):
        """All layer activations (MultiLayerNetwork.feedForward:878). Debug /
        inspection path — not jitted."""
        rngs = self._layer_rngs(self._next_rng()) if train else None
        _, _, _, _, acts = self._forward(
            self.params, self.state, x, train=train, rngs=rngs, collect=True
        )
        return acts

    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    # -- loss --------------------------------------------------------------
    def _loss(self, params, state, x, y, fmask, lmask, rngs, carries=None, train=True,
              ex_weight=None, deterministic=False):
        """Average score incl. L1/L2 penalties; returns (loss, (new_state, carries))."""
        a, new_state, new_carries, prop_mask, _ = self._forward(
            params, state, x, train=train, rngs=rngs, fmask=fmask,
            carries=carries, upto=len(self.layers) - 1, ex_weight=ex_weight,
            deterministic=deterministic,
        )
        out_layer = self.layers[-1]
        out_mask = lmask if lmask is not None else prop_mask
        loss = out_layer.score(params[-1], a, y, mask=out_mask, average=True)
        # Unconditional: wrapper layers (Bidirectional etc.) delegate to their
        # inner layer's l1/l2 even when the wrapper's own are zero.
        reg = sum(l.regularization_penalty(p) for l, p in zip(self.layers, params))
        return loss + reg, (new_state, new_carries)

    # -- jitted step -------------------------------------------------------
    def _make_step(self, with_carries: bool) -> StepProgram:
        site = "mln.step.tbptt" if with_carries else "mln.step"
        return StepProgram(self._step_body(with_carries), site, model=self,
                           hits_site="mln.fit")

    def _step_body(self, with_carries: bool, grad_exchange=None):
        """The pure training-step closure. ``grad_exchange`` (a
        ``parallel.grads.GradExchange``) replaces the per-layer update loop
        with an explicit cross-replica exchange; the body then runs under
        shard_map with per-replica local batches, the opt_state slot carries
        ``(opt_state, residuals)``, and loss/state are replica-means — the
        step's signature and return arity are unchanged."""
        from deeplearning4j_tpu.train import resilience

        layers = self.layers
        # divergence-guard skip_batch: the accept/reject select is traced
        # INTO the step (device-side; no extra host sync)
        guard = getattr(self, "divergence_guard", None)
        g_skip = bool(guard is not None and guard.policy == "skip_batch")
        g_limit = None if guard is None else guard.spike_limit
        # gradient-accumulation micro-batch count, baked at step-build time
        accum = _grad_accum_from_env()

        def step(params, opt_state, state, it, rng, x, y, fmask, lmask, carries,
                 ex_weight=None):
            # python body runs once per trace → counts actual compiles
            bucketing.telemetry().record_trace("mln.step", np.shape(x))
            if grad_exchange is not None:
                opt_state, residuals = opt_state
            batch = (x, y, fmask, lmask, ex_weight)
            # phase spans here run at TRACE time (the python body executes
            # once per compile): they attribute compile cost per phase and
            # nest under the enclosing fit/compile span in the trace export.
            # Runtime per-phase wall time needs the split-dispatch mode
            # (DL4J_TPU_PHASE_SPANS=1, _fit_batch_phases).
            if not with_carries and _accum_applicable(accum, batch):
                # DL4J_TPU_GRAD_ACCUM: scan over micro-batches, average the
                # grads, run the (single) update/exchange below on the mean —
                # grad_exchange therefore still exchanges ONCE per step
                def make_loss_fn(mb, st, k):
                    x_i, y_i, fm_i, lm_i, ew_i = mb
                    rngs_i = list(jax.random.split(k, len(layers)))

                    def loss_fn(p):
                        return self._loss(p, st, x_i, y_i, fm_i, lm_i, rngs_i,
                                          None, ex_weight=ew_i)

                    return loss_fn

                with obs.span("phase.bwd", mode="trace"):
                    loss, new_state, grads = _accum_value_and_grad(
                        accum, params, state, batch, rng, make_loss_fn)
                new_carries = None
            else:
                rngs = list(jax.random.split(rng, len(layers)))

                def loss_fn(p):
                    return self._loss(p, state, x, y, fmask, lmask, rngs,
                                      carries if with_carries else None,
                                      ex_weight=ex_weight)

                with obs.span("phase.bwd", mode="trace"):
                    (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params)

            if grad_exchange is not None:
                loss = grad_exchange.mean_loss(loss)
                new_state = grad_exchange.mean_state(new_state)
                new_params, new_opt, new_res = grad_exchange.update(
                    grads, params, opt_state, residuals, it)
                if g_skip:
                    # loss is already the replica mean → ok is replicated
                    ok = resilience.guard_ok(loss, g_limit)
                    new_params = resilience.guard_select(ok, new_params, params)
                    new_opt = resilience.guard_select(ok, new_opt, opt_state)
                    new_res = resilience.guard_select(ok, new_res, residuals)
                    new_state = resilience.guard_select(ok, new_state, state)
                return (new_params, (new_opt, new_res), new_state,
                        new_carries, loss)

            with obs.span("phase.update", mode="trace"):
                out_params, out_opt = self._update_params(
                    params, opt_state, grads, it)
            if g_skip:
                ok = resilience.guard_ok(loss, g_limit)
                out_params = resilience.guard_select(ok, out_params, params)
                out_opt = resilience.guard_select(ok, out_opt, opt_state)
                new_state = resilience.guard_select(ok, new_state, state)
            return out_params, out_opt, new_state, new_carries, loss

        return step

    def _update_params(self, params, opt_state, grads, it):
        """The per-layer optimizer update (normalization → updater →
        constraints), shared by the fused step body and the split-dispatch
        phase mode so both paths run identical math."""
        new_params = []
        new_opt = []
        for i, (u, layer) in enumerate(zip(self._updaters, self.layers)):
            g = grads[i]
            if not g:  # param-free layer
                new_params.append(params[i])
                new_opt.append(opt_state[i])
                continue
            gn = getattr(layer, "gradient_normalization", None)
            if gn:
                g = apply_gradient_normalization(
                    gn, getattr(layer, "gradient_normalization_threshold", 1.0), g
                )
            upd, new_s = u.update(g, opt_state[i], params[i], it)
            p_new = jax.tree_util.tree_map(lambda p, d: p - d, params[i], upd)
            if getattr(layer, "constraints", None):
                # post-update projection, fused into the same executable
                from deeplearning4j_tpu.nn.constraints import apply_constraints

                p_new = apply_constraints(layer, p_new)
            new_params.append(p_new)
            new_opt.append(new_s)
        return tuple(new_params), tuple(new_opt)

    # -- split-dispatch phase profiling ------------------------------------
    def _make_phase_fns(self):
        """Three executables for the DL4J_TPU_PHASE_SPANS=1 profiling mode:
        forward-only loss, value_and_grad (its forward recompute is the
        price of splitting — bwd wall includes one fwd), and the optimizer
        update. Same loss/update code as the fused step; the same rng key
        feeds fwd and bwd so both see identical dropout draws. Nothing
        donates: arguments are re-used across phases, and a profiling mode
        measures wall time, not allocator behavior."""
        layers = self.layers

        def fwd(params, state, x, y, fmask, lmask, rng, ex_weight):
            bucketing.telemetry().record_trace("mln.phase.fwd", np.shape(x))
            rngs = list(jax.random.split(rng, len(layers)))
            loss, _ = self._loss(params, state, x, y, fmask, lmask, rngs,
                                 None, ex_weight=ex_weight)
            return loss

        def bwd(params, state, x, y, fmask, lmask, rng, ex_weight):
            bucketing.telemetry().record_trace("mln.phase.bwd", np.shape(x))
            rngs = list(jax.random.split(rng, len(layers)))

            def loss_fn(p):
                return self._loss(p, state, x, y, fmask, lmask, rngs, None,
                                  ex_weight=ex_weight)

            (loss, (new_state, _)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return loss, new_state, grads

        def upd(params, opt_state, grads, it):
            bucketing.telemetry().record_trace("mln.phase.update", ())
            return self._update_params(params, opt_state, grads, it)

        return (
            StepProgram(fwd, "mln.phase.fwd", donate_argnums=(),
                        aot_wrap=False),
            StepProgram(bwd, "mln.phase.bwd", donate_argnums=(),
                        aot_wrap=False),
            StepProgram(upd, "mln.phase.update", donate_argnums=(),
                        aot_wrap=False),
        )

    def _get_phase_fns(self):
        if getattr(self, "_phase_fns", None) is None:
            self._phase_fns = self._make_phase_fns()
        return self._phase_fns

    def _fit_batch_phases(self, x, y, fm, lm, ew):
        """One training step as three blocked dispatches under nested
        phase.fwd/phase.bwd/phase.update spans (inside the caller's
        mln.fit_batch span). The block_until_ready barriers are the POINT
        of this mode — per-phase wall times instead of one fused opaque
        dispatch — and also why it is opt-in: blocking forfeits pipeline
        overlap, so it profiles, never trains by default. Parameter math is
        identical to the fused step; the divergence-guard fused select and
        grad-exchange variants fall back to the fused path in _fit_batch."""
        fwd, bwd, upd = self._get_phase_fns()
        it = jnp.asarray(self.iteration, jnp.int32)
        rng = self._next_rng()
        ew_a = jnp.asarray(ew, self.dtype) if ew is not None else None
        with obs.span("phase.fwd"):
            loss_fwd = fwd(self.params, self.state, x, y, fm, lm, rng, ew_a)
            jax.block_until_ready(loss_fwd)
        with obs.span("phase.bwd"):
            loss, new_state, grads = bwd(
                self.params, self.state, x, y, fm, lm, rng, ew_a)
            jax.block_until_ready(grads)
        with obs.span("phase.update"):
            self.params, self.opt_state = upd(
                self.params, self.opt_state, grads, it)
            jax.block_until_ready(self.params)
        self.state = new_state
        self.iteration += 1
        return loss

    def _make_chain_step(self):
        """K train steps per DISPATCH: lax.scan of the step body over
        stacked [K, B, ...] minibatches. Small models are dispatch-bound
        (a ~4 ms host->device floor per call through remote links —
        docs/PERF.md LeNet); one dispatch covering K steps amortizes it.
        Per-step rngs derive as fold_in(rng, i) — identical math to the
        per-step path for models that draw no randomness (no dropout /
        weight noise), a different-but-equivalent stream otherwise."""
        body_step = self._step_body(False)

        def chain(params, opt_state, state, it0, rng, xs, ys):
            # own cost-attribution site: the chained executable covers K
            # steps per dispatch, so its static costs must not be filed
            # under the per-step mln.step site
            bucketing.telemetry().record_trace("mln.chain", np.shape(xs))

            def body(carry, inp):
                p, o, s, i = carry
                x, y = inp
                k = jax.random.fold_in(rng, i)
                p, o, s, _, loss = body_step(p, o, s, it0 + i, k, x, y,
                                             None, None, ())
                return (p, o, s, i + 1), loss

            (p, o, s, _), losses = jax.lax.scan(
                body, (params, opt_state, state, jnp.asarray(0, jnp.int32)),
                (xs, ys))
            return p, o, s, losses

        # aot_wrap=False: the chained executable bypasses the AOT warm
        # dispatcher (its [K, B, ...] signature never matches the ladder);
        # StepProgram still runs the lazy cost-exemplar harvest for it
        return StepProgram(chain, "mln.chain", aot_wrap=False)

    def _get_chain_step(self):
        if getattr(self, "_chain_step_fn", None) is None:
            self._chain_step_fn = self._make_chain_step()
        return self._chain_step_fn

    def _get_step_fn(self, with_carries: bool):
        if with_carries:
            if self._tbptt_step_fn is None:
                self._tbptt_step_fn = self._make_step(True)
            return self._tbptt_step_fn
        if self._step_fn is None:
            self._step_fn = self._make_step(False)
        return self._step_fn

    # -- training ----------------------------------------------------------
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def _chain_k(self) -> int:
        """Steps chained per dispatch in fit()'s hot loop (0 = per-step).
        DL4J_TPU_CHAIN_STEPS forces a count; "auto" chains 8 only for
        models that draw NO randomness (identical math to per-step) and
        are small enough to be dispatch-bound (docs/PERF.md LeNet)."""
        uses_rng = any(l.uses_rng() for l in self.layers)
        return _chain_k_from_env(uses_rng, self.num_params())

    def _fit_chained(self, buf) -> None:
        """One dispatch covering len(buf) train steps (lax.scan of the step
        body over stacked minibatches)."""
        chain = self._get_chain_step()
        xs = jnp.stack([_cast_input(x, self.dtype) for x, _ in buf])
        ys = jnp.stack([_cast_labels(y, self.dtype) for _, y in buf])
        args = (self.params, self.opt_state, self.state,
                jnp.asarray(self.iteration, jnp.int32), self._next_rng(),
                xs, ys)
        # the StepProgram runs the lazy cost-exemplar harvest itself (aval
        # capture only on the rare compile path — donation invalidates
        # buffers, not shapes/dtypes)
        self.params, self.opt_state, self.state, _ = chain(*args)
        self.iteration += len(buf)

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            resume_from=None):
        """Train. ``data``: (x, y[, fmask[, lmask]]) arrays, an iterable of
        such batches, or a callable returning a fresh iterable per epoch
        (DataSetIterator equivalent).

        ``resume_from``: a CheckpointListener directory — restore the newest
        VALID checkpoint (params/opt/state, RNG key, iteration/epoch, batch
        position) and continue. ``epochs`` then counts the TOTAL budget
        (already-completed epochs are subtracted) and the interrupted epoch
        skips its already-consumed batches, so the resumed run replays the
        exact RNG/batch stream of an uninterrupted one (docs/ROBUSTNESS.md)."""
        from deeplearning4j_tpu.train import resilience
        from deeplearning4j_tpu.train.listeners import close_listeners

        if self.params is None:
            self.init()
        resume_skip = 0
        if resume_from is not None:
            if resilience.resume(self, resume_from) is not None:
                resume_skip = int(getattr(self, "batch_in_epoch", 0))
                epochs = max(epochs - self.epoch, 0)
        import os as _os

        if _os.environ.get("DL4J_TPU_TUNE"):
            # persisted tuner winner for this (signature, backend,
            # toolchain) — applied BEFORE chain_k/warm/step-build read
            # their envs, so it shapes everything compiled below
            from deeplearning4j_tpu import tune as _tune

            _tune.maybe_apply(self, "fit")
        tbptt = self.conf.backprop_type == "tbptt"
        sgd = self.conf.optimization_algo in (
            "stochastic_gradient_descent", "sgd")
        guard = getattr(self, "divergence_guard", None)
        chain_k = (self._chain_k()
                   if sgd and not self.listeners and guard is None else 0)
        if aot.enabled() and sgd and not tbptt and chain_k <= 1:
            # time-to-first-step becomes a warm-path number: the step
            # executable for the exact first-batch signature is compiled
            # (or already bundle-restored) before the epoch loop dispatches
            aot.warm_fit(self, data, batch_size)
        try:
            for _ in range(epochs):
                skip_n, resume_skip = resume_skip, 0
                self.batch_in_epoch = skip_n
                for l in self.listeners:
                    l.on_epoch_start(self, self.epoch)
                source = data() if callable(data) else data
                buf: list = []
                # pad every batch (incl. the partial tail) to ONE row count
                # with a uniform ew/lmask calling convention → one compiled
                # step. The chained path needs bare (x, y) batches, so it
                # opts out.
                pad_target = (_fit_pad_target(source, batch_size)
                              if sgd and chain_k <= 1
                              and bucketing.bucketing_enabled() else None)

                def flush(full: bool):
                    # full K-groups go out as ONE dispatch; tails use the
                    # per-step path (a different K would be a fresh compile)
                    if not buf:
                        return
                    with obs.span("mln.fit_batch", batches=len(buf)):
                        if full and len(buf) > 1:
                            self._fit_chained(buf)
                        else:
                            for bx, by in buf:
                                self._fit_batch(bx, by, None, None)
                    buf.clear()

                def batches():
                    it = _iter_batches(source, batch_size)
                    # resume: the interrupted epoch's consumed batches are
                    # skipped HERE, before padding/prefetch and without
                    # touching the RNG — the restored key is already past them
                    for _ in range(skip_n):
                        if next(it, None) is None:
                            return
                    for x, y, fm, lm in it:
                        # real-row count taken HERE, before padding, so the
                        # fit loop never syncs ew back from device to learn it
                        n = len(x)
                        if pad_target is not None and not (tbptt and np.ndim(x) == 3):
                            yield bucketing.pad_fit_batch(
                                x, y, fm, lm, pad_target, site="mln.fit") + (n,)
                        else:
                            yield (x, y, fm, lm, None, n)

                stream = batches()
                if sgd and _device_prefetch_enabled():
                    # overlap next batch's host→device transfer with this
                    # step's compute (double buffering); AFTER padding,
                    # which is host-side
                    from deeplearning4j_tpu.datasets.iterator import prefetch_to_device

                    stream = prefetch_to_device(stream)
                for x, y, fm, lm, ew, n_real in stream:
                    chainable = (
                        chain_k > 1 and fm is None and lm is None
                        and not (tbptt and np.ndim(x) == 3)
                        and (not buf or _batch_sig((x, y))
                             == _batch_sig((buf[0][0], buf[0][1])))
                    )
                    if chainable:
                        buf.append((x, y))
                        self.batch_in_epoch += 1
                        if len(buf) == chain_k:
                            flush(True)
                        continue
                    flush(False)
                    with obs.span("mln.fit_batch"):
                        if not sgd:
                            score = self._fit_solver(x, y, fm, lm)
                        elif tbptt and np.ndim(x) == 3:
                            score = self._fit_tbptt(x, y, fm, lm)
                        else:
                            score = self._fit_batch(x, y, fm, lm, ew=ew)
                    self.batch_in_epoch += 1
                    if guard is not None:
                        guard.observe(self, score)
                    # score is a device scalar; only sync the host when a
                    # listener actually consumes it (keeps dispatch async);
                    # n_real came from the pre-padding host side of the stream
                    if self.listeners:
                        score = float(score)  # graftlint: disable=host-sync
                        resilience.note_score(score)
                        for l in self.listeners:
                            l.iteration_done(self, self.iteration, score, n_real)
                flush(False)
                if guard is not None:
                    guard.flush(self)
                for l in self.listeners:
                    l.on_epoch_end(self, self.epoch)
                self.epoch += 1
        finally:
            # a run ending inside a ProfilerListener [start, stop) window
            # (normally or via an exception/chaos preempt) must not leak an
            # open jax.profiler trace
            close_listeners(self.listeners)
        return self

    def _fit_batch(self, x, y, fm, lm, ew=None):
        """One step. Returns the loss as a DEVICE scalar — callers decide
        whether to sync (fit() only syncs when listeners are attached).
        ``ew``: optional per-example validity weight (ParallelWrapper padding)
        consumed by batch-coupled layers — see _forward."""
        from deeplearning4j_tpu.train import resilience

        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_preempt(self.iteration)
            chaos.maybe_slow(self.iteration)
            x = chaos.maybe_nan_batch(self.iteration, x)
        x = _cast_input(x, self.dtype)
        y = _cast_labels(y, self.dtype)
        fm = jnp.asarray(fm, self.dtype) if fm is not None else None
        lm = jnp.asarray(lm, self.dtype) if lm is not None else None
        if (obs.phase_spans_enabled()
                and getattr(self, "divergence_guard", None) is None):
            # opt-in profiling mode: three blocked dispatches under nested
            # phase spans; the fused step (guard select, donation, chaining)
            # stays the production path
            return self._fit_batch_phases(x, y, fm, lm, ew)
        step = self._get_step_fn(False)
        # dispatch() runs the step, then the retrace-guard check the program
        # owns: traces land at mln.step (inside the jitted body), bucket
        # traffic lands at mln.fit (pad_fit_batch) — the guard joins the two
        self.params, self.opt_state, self.state, _, loss = step.dispatch(
            self.params, self.opt_state, self.state,
            jnp.asarray(self.iteration, jnp.int32), self._next_rng(),
            x, y, fm, lm, (),
            ex_weight=jnp.asarray(ew, self.dtype) if ew is not None else None,
        )
        self.iteration += 1
        return loss

    def _fit_solver(self, x, y, fm, lm):
        """Non-SGD OptimizationAlgorithm path (Solver.java dispatch): run
        conf.solver_iterations deterministic solver steps on this batch.
        The Solver (and its jitted value_and_grad) is cached on the model so
        successive batches/epochs reuse one compiled executable per batch
        shape instead of retracing (round-2 advisor finding)."""
        from deeplearning4j_tpu.train.solvers import Solver

        solver = getattr(self, "_solver", None)
        if solver is None or solver.algorithm != self.conf.optimization_algo:
            solver = Solver(self, self.conf.optimization_algo)
            self._solver = solver
        loss = solver.optimize((x, y, fm, lm), iterations=self.conf.solver_iterations)
        self.iteration += 1
        return loss

    def _fit_tbptt(self, x, y, fm, lm):
        """Truncated BPTT: chunk the time axis, carry RNN state across chunks
        (doTruncatedBPTT:1514 — forward/backward chunk length unified)."""
        from deeplearning4j_tpu.train import resilience

        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_preempt(self.iteration)
            chaos.maybe_slow(self.iteration)
        step = self._get_step_fn(True)
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        carries = tuple(
            l.initial_carry(x.shape[0], self.dtype) if f else ()
            for l, f in zip(self.layers, self._carry_flags)
        )
        total, nchunks = 0.0, 0
        for t0 in range(0, T, L):
            sl = slice(t0, min(t0 + L, T))
            xc = jnp.asarray(x[:, sl], self.dtype)
            # time-sliced labels: one-hot [B,T,C] AND sparse integer [B,T];
            # rank-2 FLOAT labels (sequence-level heads) pass through whole
            y_sliced = (y is not None and (np.ndim(y) == 3 or (
                np.ndim(y) == 2 and np.dtype(_sig_dtype(y)).kind in "iu")))
            yc = _cast_labels(y[:, sl] if y_sliced else y, self.dtype)
            fmc = jnp.asarray(fm[:, sl], self.dtype) if fm is not None else None
            lmc = jnp.asarray(lm[:, sl], self.dtype) if lm is not None else None
            self.params, self.opt_state, self.state, carries, loss = step(
                self.params, self.opt_state, self.state,
                jnp.asarray(self.iteration, jnp.int32), self._next_rng(),
                xc, yc, fmc, lmc, carries,
            )
            # truncation is structural: each chunk is its own jitted step, so
            # the concrete carry arrays carry values, never gradients
            total = total + loss  # device-side accumulation, no host sync
            nchunks += 1
            self.iteration += 1
        return total / max(nchunks, 1)

    # -- inference ---------------------------------------------------------
    def _get_output_fn(self):
        """The jitted inference entry point, AOT-wrapped so warmup
        (``nn/aot.py``) can pre-compile every ladder bucket and bundle
        restore can install persisted executables."""
        if self._output_fn is None:
            def fwd(params, state, x, fmask):
                # python body runs once per trace → counts actual compiles
                bucketing.telemetry().record_trace("mln.output", np.shape(x))
                a, _, _, _, _ = self._forward(params, state, x, train=False, rngs=None,
                                              fmask=fmask)
                return a

            self._output_fn = StepProgram(
                fwd, "mln.output", model=self, donate_argnums=())
        return self._output_fn

    def output(self, x, train: bool = False, fmask=None):
        """Final-layer post-activation output (MultiLayerNetwork.output:2005),
        jit-compiled inference path.

        Batch rows are padded up to the shared bucket ladder before dispatch
        (and sliced back off) so mixed caller batch sizes share one compiled
        executable per bucket — inference is row-independent (BatchNorm uses
        running stats when train=False), so zero-pad rows are dead compute,
        not a numerics change. Disable via DL4J_TPU_BUCKETING=0."""
        self._get_output_fn()
        x = _cast_input(x, self.dtype)
        fmask = jnp.asarray(fmask, self.dtype) if fmask is not None else None
        n = x.shape[0]
        with obs.span("mln.output"):
            if bucketing.bucketing_enabled() and n > 0:
                target = bucketing.bucket_size(n)
                bucketing.telemetry().record_hit("mln.output", n, target)
                if target > n:
                    x = bucketing.pad_rows_zero(x, target)
                    fmask = bucketing.pad_rows_zero(fmask, target)
                    return bucketing.unpad(
                        self._output_fn.dispatch(
                            self.params, self.state, x, fmask), n)
            out = self._output_fn.dispatch(self.params, self.state, x, fmask)
        return out

    def predict(self, x) -> np.ndarray:
        # argmax on device: transfer the [B] class indices, not the full
        # [B, C] activation matrix
        idx = jnp.argmax(self.output(x), axis=-1)
        return np.asarray(idx)  # graftlint: disable=host-sync

    def score(self, batch_or_x, y=None, fmask=None, lmask=None,
              train: bool = False) -> float:
        """Average loss on a batch (MultiLayerNetwork.score(data, training)).

        ``train=True`` scores with training-mode statistics — normalization
        layers use the batch's own mean/var instead of the (one-step-stale)
        running estimates — while dropout / weight noise stay disabled, so
        the result is deterministic. This is the right mode for "did the
        training loss go down" checks on deep BatchNorm stacks, where eval
        statistics lag the params by a step and the error compounds through
        every BN layer."""
        if y is None:
            x, y, fmask, lmask = _as_batch(batch_or_x)
        else:
            x = batch_or_x
        loss, _ = self._loss(
            self.params, self.state,
            _cast_input(x, self.dtype), _cast_labels(y, self.dtype),
            jnp.asarray(fmask, self.dtype) if fmask is not None else None,
            jnp.asarray(lmask, self.dtype) if lmask is not None else None,
            rngs=None,
            train=train,
            deterministic=True,
        )
        return float(loss)

    # -- evaluation --------------------------------------------------------
    def _output_mask(self, fm, lm):
        """Mask for scoring/eval at the network output: the labels mask, or
        the features mask propagated through the layer stack (matches _loss)."""
        if lm is not None:
            return np.asarray(lm)
        if fm is None:
            return None
        mask = jnp.asarray(fm, self.dtype)
        for layer, it in zip(self.layers, self.layer_input_types):
            mask = layer.propagate_mask(mask, it)
            if mask is None:
                return None
        return np.asarray(mask)

    def evaluate(self, data, batch_size: Optional[int] = None, top_n: int = 1):
        from deeplearning4j_tpu.eval import Evaluation

        ev = Evaluation(top_n=top_n)
        for x, y, fm, lm in _iter_batches(data, batch_size):
            preds = self.output(x, fmask=fm)
            ev.eval(np.asarray(y), np.asarray(preds), mask=self._output_mask(fm, lm))
        return ev

    def evaluate_regression(self, data, batch_size: Optional[int] = None):
        from deeplearning4j_tpu.eval import RegressionEvaluation

        ev = RegressionEvaluation()
        for x, y, fm, lm in _iter_batches(data, batch_size):
            preds = self.output(x, fmask=fm)
            ev.eval(np.asarray(y), np.asarray(preds), mask=self._output_mask(fm, lm))
        return ev

    def evaluate_roc(self, data, batch_size: Optional[int] = None, num_bins: int = 200):
        from deeplearning4j_tpu.eval import ROC

        roc = ROC(num_bins)
        for x, y, fm, lm in _iter_batches(data, batch_size):
            preds = self.output(x, fmask=fm)
            roc.eval(np.asarray(y), np.asarray(preds))
        return roc

    # -- streaming RNN inference (rnnTimeStep:2371) ------------------------
    def rnn_time_step(self, x):
        """Feed one or more timesteps, carrying RNN state between calls."""
        x = _cast_input(x, self.dtype)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        leaves = (
            jax.tree_util.tree_leaves(self._rnn_carries) if self._rnn_carries is not None else []
        )
        if self._rnn_carries is None or (leaves and leaves[0].shape[0] != x.shape[0]):
            self._rnn_carries = tuple(
                l.initial_carry(x.shape[0], self.dtype) if f else ()
                for l, f in zip(self.layers, self._carry_flags)
            )
        a, _, new_carries, _, _ = self._forward(
            self.params, self.state, x, train=False, rngs=None, carries=self._rnn_carries
        )
        self._rnn_carries = new_carries
        return a[:, 0, :] if squeeze and a.ndim == 3 else a

    def rnn_clear_previous_state(self):
        self._rnn_carries = None

    # -- persistence hooks (utils/serialization.py drives these) -----------
    def clone(self) -> "MultiLayerNetwork":
        m = MultiLayerNetwork(self.conf)
        if self.params is not None:
            m.init()
            # Deep copy: the jitted step DONATES params/opt_state/state, so
            # aliasing the live buffers would leave the clone pointing at
            # deleted arrays after the next fit() on either model.
            copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
            m.params = copy(self.params)
            m.state = copy(self.state)
            m.opt_state = copy(self.opt_state)
            m.iteration = self.iteration
            m.epoch = self.epoch
        return m

    def summary(self) -> str:
        lines = [f"{'idx':<4} {'type':<22} {'output':<24} {'params':<10}"]
        for i, (l, it) in enumerate(zip(self.layers, self.layer_input_types)):
            out = l.output_type(it)
            n = (
                sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params[i]))
                if self.params is not None
                else "?"
            )
            lines.append(f"{i:<4} {l._type_name:<22} {str(out.batch_shape())[0:24]:<24} {n:<10}")
        lines.append(f"Total params: {self.num_params() if self.params is not None else '?'}")
        return "\n".join(lines)
