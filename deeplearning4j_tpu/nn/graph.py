"""ComputationGraph: arbitrary-DAG models (multi-input / multi-output).

Capability parity with the reference's nn/graph/ComputationGraph.java
(3,902 LoC: vertices:143, topologicalOrder:152, init:377, fit:857-1146,
calcBackpropGradients:1942, output:1754-1878), the conf classes under
nn/conf/graph/ (ElementWiseVertex, MergeVertex, StackVertex, UnstackVertex,
SubsetVertex, ScaleVertex, ShiftVertex, L2Vertex, L2NormalizeVertex,
ReshapeVertex, PreprocessorVertex, rnn/LastTimeStepVertex,
rnn/DuplicateToTimeSeriesVertex, rnn/ReverseTimeSeriesVertex) and
nn/conf/ComputationGraphConfiguration.java — re-designed TPU-first:

- One pure jitted train step over the whole DAG: forward walks the
  topological order once inside the trace, loss is the sum over all output
  heads, backward is autodiff of the whole step. The reference instead walks
  `GraphVertex.doForward/doBackward` objects with per-op JNI dispatch and
  hand-written epsilon accumulation at fan-in vertices — XLA's autodiff does
  that accumulation for free.
- Params are a dict {vertex_name: layer params}, not one flattened view
  split into per-vertex subsets (ComputationGraph.init:426-470).
- NHWC / [batch, time, feat] layouts throughout (TPU tiling), so MergeVertex
  is always a last-axis concat regardless of input kind.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.analysis import retrace_guard
from deeplearning4j_tpu.nn import aot
from deeplearning4j_tpu.nn.config import LayerConfig, layer_from_dict, _encode_value
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrent
from deeplearning4j_tpu.nn.model import _cast_input, _cast_labels, _sig_dtype
from deeplearning4j_tpu.nn.preprocessors import infer_preprocessor
from deeplearning4j_tpu.utils import bucketing
from deeplearning4j_tpu.train.updaters import (
    apply_gradient_normalization,
    make_updater,
    normalize_updater,
    scale_lr,
)

# ---------------------------------------------------------------------------
# Vertex configs
# ---------------------------------------------------------------------------

vertex_registry: Dict[str, type] = {}


def register_vertex(type_name: str):
    def deco(cls):
        cls._vtype_name = type_name
        vertex_registry[type_name] = cls
        return cls

    return deco


@dataclass
class GraphVertex:
    """Base for non-layer DAG nodes (nn/conf/graph/GraphVertex.java).

    Contract (all pure; list-valued inputs):
    - ``output_type(input_types) -> InputType``
    - ``init(key, input_types, dtype) -> params`` ({} default — most vertices
      are param-free)
    - ``apply(params, state, xs, *, train, rng, masks) -> (y, new_state)``
    - ``propagate_mask(masks, input_types) -> mask``
    """

    _vtype_name = "vertex"
    trainable = True
    l1 = 0.0
    l2 = 0.0
    updater = None

    def to_dict(self) -> dict:
        d = {"@vtype": self._vtype_name}
        for f in dataclasses.fields(self):
            d[f.name] = _encode_value(getattr(self, f.name))
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphVertex":
        tag = d.get("@vtype")
        if tag not in vertex_registry:
            raise ValueError(f"Unknown vertex type '{tag}'. Known: {sorted(vertex_registry)}")
        cls = vertex_registry[tag]
        names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in d.items() if k in names}
        # JSON arrays -> tuples for shape-like fields
        kwargs = {k: tuple(v) if isinstance(v, list) else v for k, v in kwargs.items()}
        return cls(**kwargs)

    # -- contract defaults -------------------------------------------------
    def output_type(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def init(self, key, input_types: List[InputType], dtype=jnp.float32):
        return {}

    def init_state(self, input_types: List[InputType]):
        return {}

    def apply(self, params, state, xs: List[jax.Array], *, train=False, rng=None, masks=None):
        raise NotImplementedError

    def propagate_mask(self, masks, input_types: List[InputType]):
        for m in masks or ():
            if m is not None:
                return m
        return None

    def regularization_penalty(self, params):
        return jnp.asarray(0.0, jnp.float32)


@register_vertex("merge")
@dataclass
class MergeVertex(GraphVertex):
    """Concat along the feature/channel axis (MergeVertex.java). NHWC makes
    this the last axis for every input kind."""

    def output_type(self, input_types):
        it0 = input_types[0]
        if it0.kind == "conv":
            return InputType.convolutional(
                it0.height, it0.width, sum(t.channels for t in input_types)
            )
        if it0.kind == "recurrent":
            return InputType.recurrent(sum(t.size for t in input_types), it0.timesteps)
        return InputType.feed_forward(sum(t.flat_size() for t in input_types))

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        return jnp.concatenate(xs, axis=-1), state


@register_vertex("elementwise")
@dataclass
class ElementWiseVertex(GraphVertex):
    """Pointwise add/subtract/product/average/max across inputs
    (ElementWiseVertex.java — the residual-connection workhorse)."""

    op: str = "add"

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        if self.op == "add":
            y = sum(xs[1:], xs[0])
        elif self.op == "subtract":
            y = xs[0] - xs[1]
        elif self.op == "product":
            y = xs[0]
            for x in xs[1:]:
                y = y * x
        elif self.op == "average":
            y = sum(xs[1:], xs[0]) / len(xs)
        elif self.op == "max":
            y = xs[0]
            for x in xs[1:]:
                y = jnp.maximum(y, x)
        else:
            raise ValueError(f"Unknown elementwise op '{self.op}'")
        return y, state


@register_vertex("stack")
@dataclass
class StackVertex(GraphVertex):
    """Concat along the batch axis (StackVertex.java) — used with Unstack for
    weight sharing across branches."""

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        return jnp.concatenate(xs, axis=0), state


@register_vertex("unstack")
@dataclass
class UnstackVertex(GraphVertex):
    """Slice batch segment ``from_index`` of ``stack_size`` equal parts
    (UnstackVertex.java)."""

    from_index: int = 0
    stack_size: int = 1

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        x = xs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step : (self.from_index + 1) * step], state


@register_vertex("subset")
@dataclass
class SubsetVertex(GraphVertex):
    """Feature range [from_index, to_index] INCLUSIVE (SubsetVertex.java)."""

    from_index: int = 0
    to_index: int = 0

    def output_type(self, input_types):
        n = self.to_index - self.from_index + 1
        it = input_types[0]
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timesteps)
        if it.kind == "conv":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feed_forward(n)

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        return xs[0][..., self.from_index : self.to_index + 1], state


@register_vertex("scale")
@dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        return xs[0] * self.scale, state


@register_vertex("shift")
@dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        return xs[0] + self.shift, state


@register_vertex("l2")
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance of two inputs -> [batch, 1] (L2Vertex.java, used
    by triplet-loss nets like FaceNet)."""

    eps: float = 1e-8

    def output_type(self, input_types):
        return InputType.feed_forward(1)

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        a = xs[0].reshape(xs[0].shape[0], -1)
        b = xs[1].reshape(xs[1].shape[0], -1)
        d = jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1, keepdims=True) + self.eps)
        return d, state


@register_vertex("l2normalize")
@dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over all non-batch axes (L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        x = xs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / norm, state


@register_vertex("reshape")
@dataclass
class ReshapeVertex(GraphVertex):
    """Reshape to ``shape`` (batch axis = -1 allowed) (ReshapeVertex.java)."""

    shape: Tuple[int, ...] = ()
    output: Optional[dict] = None  # explicit InputType dict for shape inference

    def output_type(self, input_types):
        if self.output is not None:
            return InputType.from_dict(dict(self.output))
        s = [d for d in self.shape if d != -1]
        if len(s) == 1:
            return InputType.feed_forward(s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        return input_types[0]

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        return xs[0].reshape(self.shape), state


@register_vertex("preprocessor")
@dataclass
class PreprocessorVertex(GraphVertex):
    """Wraps any param-free LayerConfig (the preprocessors) as a DAG node
    (PreprocessorVertex.java)."""

    preprocessor: Any = None

    def output_type(self, input_types):
        return self.preprocessor.output_type(input_types[0])

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        y, _ = self.preprocessor.apply({}, {}, xs[0], train=train, rng=rng,
                                       mask=masks[0] if masks else None)
        return y, state

    def to_dict(self):
        return {"@vtype": self._vtype_name, "preprocessor": self.preprocessor.to_dict()}

    @staticmethod
    def _decode(d):
        return PreprocessorVertex(preprocessor=layer_from_dict(d["preprocessor"]))


@register_vertex("last_time_step")
@dataclass
class LastTimeStepVertex(GraphVertex):
    """[b,t,f] -> [b,f]: last time step, or last UNMASKED step when the named
    network input has a mask (rnn/LastTimeStepVertex.java)."""

    mask_input: Optional[str] = None

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        x = xs[0]
        m = masks[0] if masks else None
        if m is None:
            return x[:, -1, :], state
        # last index where mask==1 (handles left-padded/ALIGN_END masks)
        T = x.shape[1]
        rev = jnp.flip(m > 0, axis=1)
        idx = (T - 1 - jnp.argmax(rev, axis=1)).astype(jnp.int32)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :], state

    def propagate_mask(self, masks, input_types):
        return None


@register_vertex("duplicate_to_time_series")
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[b,f] -> [b,t,f], t taken from the second runtime input (the reference
    names a network input; here the builder wires that input's activation in
    as input #2 so t is known inside the trace)
    (rnn/DuplicateToTimeSeriesVertex.java)."""

    def output_type(self, input_types):
        t = input_types[1].timesteps if len(input_types) > 1 else None
        return InputType.recurrent(input_types[0].flat_size(), t)

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        x, ref = xs[0], xs[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], ref.shape[1], x.shape[-1])), state

    def propagate_mask(self, masks, input_types):
        return masks[1] if masks and len(masks) > 1 else None


@register_vertex("reverse_time_series")
@dataclass
class ReverseTimeSeriesVertex(GraphVertex):
    """Reverse the time axis; with a mask, only the valid prefix is reversed
    (rnn/ReverseTimeSeriesVertex.java)."""

    def apply(self, params, state, xs, *, train=False, rng=None, masks=None):
        x = xs[0]
        m = masks[0] if masks else None
        if m is None:
            return x[:, ::-1, :], state
        lengths = jnp.sum(m > 0, axis=1).astype(jnp.int32)  # [b]
        t = x.shape[1]
        # index j -> (len-1-j) for j < len, else j (padding stays in place)
        j = jnp.arange(t)[None, :]
        idx = jnp.where(j < lengths[:, None], lengths[:, None] - 1 - j, j)
        return jnp.take_along_axis(x, idx[:, :, None], axis=1), state


# ---------------------------------------------------------------------------
# Configuration + builder
# ---------------------------------------------------------------------------


@dataclass
class VertexSpec:
    """One DAG node: a LayerConfig or a GraphVertex plus its input names."""

    config: Any
    inputs: Tuple[str, ...]

    def is_layer(self) -> bool:
        return isinstance(self.config, LayerConfig)


@dataclass
class ComputationGraphConfiguration:
    """DAG config (ComputationGraphConfiguration.java, 928 LoC). JSON
    round-trip is the long-lived artifact contract (SURVEY §5.6)."""

    inputs: Tuple[str, ...] = ()
    input_types: Dict[str, InputType] = field(default_factory=dict)
    vertices: Dict[str, VertexSpec] = field(default_factory=dict)  # insertion-ordered
    outputs: Tuple[str, ...] = ()
    seed: int = 12345
    updater: Any = "sgd"
    dtype: str = "float32"
    # Truncated BPTT over the DAG (ComputationGraph.java:950,1179
    # doTruncatedBPTT): "standard" | "tbptt". Forward/backward chunk length
    # unified, like the MLN path.
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration",
            "version": 1,
            "inputs": list(self.inputs),
            "input_types": {k: v.to_dict() for k, v in self.input_types.items()},
            "vertices": [
                {
                    "name": name,
                    "inputs": list(spec.inputs),
                    ("layer" if spec.is_layer() else "vertex"): spec.config.to_dict(),
                }
                for name, spec in self.vertices.items()
            ],
            "outputs": list(self.outputs),
            "seed": self.seed,
            "updater": _encode_value(self.updater),
            "dtype": self.dtype,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        vertices: Dict[str, VertexSpec] = {}
        for v in d["vertices"]:
            if "layer" in v:
                cfg = layer_from_dict(v["layer"])
            elif v["vertex"].get("@vtype") == "preprocessor":
                cfg = PreprocessorVertex._decode(v["vertex"])
            else:
                cfg = GraphVertex.from_dict(v["vertex"])
            vertices[v["name"]] = VertexSpec(cfg, tuple(v["inputs"]))
        return ComputationGraphConfiguration(
            inputs=tuple(d["inputs"]),
            input_types={k: InputType.from_dict(t) for k, t in d["input_types"].items()},
            vertices=vertices,
            outputs=tuple(d["outputs"]),
            seed=d.get("seed", 12345),
            updater=d.get("updater", "sgd"),
            dtype=d.get("dtype", "float32"),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """YAML twin of to_json (ComputationGraphConfiguration.toYaml)."""
        from deeplearning4j_tpu.nn.config import yaml_dump

        return yaml_dump(self.to_dict())

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.config import yaml_load

        return ComputationGraphConfiguration.from_dict(yaml_load(s))

    @staticmethod
    def builder() -> "GraphBuilder":
        return GraphBuilder()


class GraphBuilder:
    """Fluent DAG builder (ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self):
        self._inputs: List[str] = []
        self._input_types: Dict[str, InputType] = {}
        self._vertices: Dict[str, VertexSpec] = {}
        self._outputs: List[str] = []
        self._seed = 12345
        self._updater: Any = "sgd"
        self._dtype = "float32"
        self._backprop_type = "standard"
        self._tbptt_length = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        if len(types) != len(self._inputs):
            raise ValueError("set_input_types: one InputType per declared input")
        self._input_types = dict(zip(self._inputs, types))
        return self

    def add_layer(self, name: str, layer: LayerConfig, *inputs: str) -> "GraphBuilder":
        return self.add_vertex(name, layer, *inputs)

    def add_vertex(self, name: str, v: Any, *inputs: str) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"Duplicate vertex name '{name}'")
        known = set(self._inputs) | set(self._vertices)
        for i in inputs:
            if i not in known:
                raise ValueError(f"Vertex '{name}' input '{i}' is not defined (yet)")
        self._vertices[name] = VertexSpec(v, tuple(inputs))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def seed(self, s: int) -> "GraphBuilder":
        self._seed = s
        return self

    def updater(self, u: Any) -> "GraphBuilder":
        self._updater = u
        return self

    def dtype(self, d: str) -> "GraphBuilder":
        self._dtype = d
        return self

    def tbptt(self, length: int) -> "GraphBuilder":
        """Enable truncated BPTT with the given chunk length
        (GraphBuilder.backpropType(TruncatedBPTT) + tBPTT{Forward,Backward}Length)."""
        self._backprop_type = "tbptt"
        self._tbptt_length = length
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("ComputationGraph needs at least one input")
        if not self._outputs:
            raise ValueError("ComputationGraph needs at least one output")
        for o in self._outputs:
            if o not in self._vertices:
                raise ValueError(f"Output '{o}' is not a vertex")
        if set(self._input_types) != set(self._inputs):
            raise ValueError("set_input_types is required (one per input)")
        return ComputationGraphConfiguration(
            inputs=tuple(self._inputs),
            input_types=self._input_types,
            vertices=self._vertices,
            outputs=tuple(self._outputs),
            seed=self._seed,
            updater=self._updater,
            dtype=self._dtype,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_length,
            tbptt_back_length=self._tbptt_length,
        )


# ---------------------------------------------------------------------------
# Runtime model
# ---------------------------------------------------------------------------


@dataclass
class _RuntimeVertex:
    name: str
    spec: VertexSpec
    inputs: Tuple[str, ...]
    pre: Optional[LayerConfig]          # auto-inserted preprocessor (layer vertices)
    input_types: List[InputType]        # per runtime input, post-preprocessor
    out_type: InputType
    config: Any                          # resolved (n_in inferred) layer/vertex


def _tbptt_slice_t(x, sl, T, kind):
    """tBPTT time-axis chunking rule for one array.

    feat: inputs DECLARED recurrent chunk on axis 1 — [B,T,F] float streams
    and [B,T] integer token-id streams alike (kind=="feat_td"); statics pass
    whole — in particular a static 3-D side input whose middle dim happens
    to equal T (kind=="feat") must NOT be silently time-chunked.
    label: [B,T,C] one-hot or [B,T] sparse-integer. mask: [B,T]."""
    if x is None:
        return None
    nd = np.ndim(x)
    if nd == 3 and x.shape[1] == T and kind in ("feat_td", "label", "mask"):
        return x[:, sl]
    if nd == 2 and x.shape[1] == T:
        if kind in ("mask", "feat_td") or (
                kind == "label" and np.dtype(_sig_dtype(x)).kind in "iu"):
            return x[:, sl]
    return x


def _toposort(conf: ComputationGraphConfiguration) -> List[str]:
    """Kahn's algorithm over vertex names (ComputationGraph.topologicalOrder
    equivalent, computed once at build)."""
    indeg = {n: 0 for n in conf.vertices}
    dependents: Dict[str, List[str]] = {n: [] for n in conf.vertices}
    for name, spec in conf.vertices.items():
        for i in spec.inputs:
            if i in conf.vertices:
                indeg[name] += 1
                dependents[i].append(name)
    ready = [n for n, d in indeg.items() if d == 0]
    order: List[str] = []
    while ready:
        n = ready.pop(0)
        order.append(n)
        for m in dependents[n]:
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
    if len(order) != len(conf.vertices):
        cyc = sorted(set(conf.vertices) - set(order))
        raise ValueError(f"Graph has a cycle involving: {cyc}")
    return order


class ComputationGraph:
    """Stateful facade over pure jitted DAG functions; API mirrors the
    reference ComputationGraph (init/fit/output/score/evaluate)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.dtype = jnp.dtype(conf.dtype)
        self._resolve()
        self.params: Optional[dict] = None
        self.state: Optional[dict] = None
        self.opt_state: Optional[dict] = None
        self.iteration = 0
        self.epoch = 0
        self.batch_in_epoch = 0
        self._rng = jax.random.PRNGKey(conf.seed)
        self._step_fn = None
        self._tbptt_step_fn = None
        self._output_fn = None
        self._rnn_carries: Optional[dict] = None
        self.listeners: list = []
        self.divergence_guard = None
        self._lr_scale = 1.0
        self._pending_residuals = None

    # -- resolution --------------------------------------------------------
    def _resolve(self):
        conf = self.conf
        self.topo_order = _toposort(conf)
        types: Dict[str, InputType] = dict(conf.input_types)
        self.rt: Dict[str, _RuntimeVertex] = {}
        for name in self.topo_order:
            spec = conf.vertices[name]
            in_types = [types[i] for i in spec.inputs]
            pre = None
            cfg = spec.config
            if spec.is_layer():
                if len(spec.inputs) != 1:
                    raise ValueError(f"Layer vertex '{name}' must have exactly one input")
                pre = infer_preprocessor(in_types[0], cfg)
                if pre is not None:
                    in_types = [pre.output_type(in_types[0])]
                if hasattr(cfg, "with_n_in"):
                    cfg = cfg.with_n_in(cfg.infer_n_in(in_types[0]))
                out_t = cfg.output_type(in_types[0])
            else:
                out_t = cfg.output_type(in_types)
            types[name] = out_t
            self.rt[name] = _RuntimeVertex(
                name=name, spec=spec, inputs=spec.inputs, pre=pre,
                input_types=in_types, out_type=out_t, config=cfg,
            )
        self.vertex_types = types
        self.output_types = [types[o] for o in conf.outputs]
        # layer vertices with a time-stepped carry: tBPTT chunking and
        # rnnTimeStep streaming thread state through exactly these
        # (ComputationGraph.java rnnActivateUsingStoredState:1334)
        self._carry_vertices = [
            name for name in self.topo_order
            if self.rt[name].spec.is_layer()
            and isinstance(self.rt[name].config, BaseRecurrent)
            and getattr(self.rt[name].config, "SUPPORTS_CARRY", False)
        ]
        # wrapper layers holding an inner RNN (Bidirectional, MaskZero,
        # LastTimeStep): no carry channel — streaming/tBPTT would silently
        # reset their inner state every call, so those paths refuse them
        # (the reference's Bidirectional rnnTimeStep likewise throws)
        self._wrapped_rnn_vertices = [
            name for name in self.topo_order
            if self.rt[name].spec.is_layer()
            and getattr(self.rt[name].config, "rnn", None) is not None
        ]
        self._loss_vertices = [
            o for o in conf.outputs if hasattr(self.rt[o].config, "score")
        ]
        if not self._loss_vertices:
            self._loss_vertices = []  # inference-only graph is allowed
        # Stack/Unstack split or join the BATCH axis into fixed segments —
        # padding rows would land in the wrong branch, so batch bucketing
        # (output()) must stay off for these graphs
        self._has_batch_vertices = any(
            isinstance(self.rt[name].config, (StackVertex, UnstackVertex))
            for name in self.topo_order)

    # -- init --------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        key = jax.random.PRNGKey(self.conf.seed if seed is None else seed)
        keys = jax.random.split(key, max(len(self.topo_order), 1))
        self.params, self.state = {}, {}
        for k, name in zip(keys, self.topo_order):
            v = self.rt[name]
            if v.spec.is_layer():
                self.params[name] = v.config.init(k, v.input_types[0], self.dtype)
                self.state[name] = v.config.init_state(v.input_types[0])
            else:
                self.params[name] = v.config.init(k, v.input_types, self.dtype)
                self.state[name] = v.config.init_state(v.input_types)
        self._build_updaters()
        self.opt_state = {
            name: u.init(self.params[name]) for name, u in self._updaters.items()
        }
        self.iteration = 0
        self.epoch = 0
        return self

    def _build_updaters(self):
        # _lr_scale is the divergence-guard rollback backoff (resilience.py)
        scale = float(getattr(self, "_lr_scale", 1.0))
        default = scale_lr(self.conf.updater, scale)
        self._updaters = {}
        for name in self.topo_order:
            cfg = self.rt[name].config
            if not getattr(cfg, "trainable", True):
                self._updaters[name] = make_updater("noop")
            elif getattr(cfg, "updater", None) is not None:
                self._updaters[name] = make_updater(scale_lr(cfg.updater, scale))
            else:
                self._updaters[name] = make_updater(default)

    def _clear_compiled(self):
        """Drop compiled step closures (updaters or divergence-guard config
        changed — both are baked into the trace). AOT-warmed step
        executables are stale for the same reason; the output path is
        untouched (inference doesn't trace updaters or guards)."""
        self._step_fn = None
        self._tbptt_step_fn = None
        self._chain_step_fn = None
        aot.clear_sites(self, ("cg.step", "cg.step.tbptt"))

    def set_divergence_guard(self, guard) -> "ComputationGraph":
        """Install a train/resilience.DivergenceGuard (None to remove).
        Clears compiled step caches: the skip_batch policy's select is traced
        into the step executable."""
        self.divergence_guard = guard
        self._clear_compiled()
        runner = getattr(self, "_dp_runner", None)
        if runner is not None:
            runner.rebuild_step()
        return self

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))

    # -- forward -----------------------------------------------------------
    def _forward(self, params, state, inputs: Dict[str, jax.Array], *, train, rngs,
                 masks: Optional[Dict[str, Any]] = None, stop_at: Optional[set] = None,
                 collect: bool = False, ex_weight=None, carries: Optional[dict] = None,
                 deterministic: bool = False):
        """Walk topo order. Returns (acts, new_state, mask_acts, new_carries).

        ``stop_at``: vertex names whose activation should be the PRE-output
        value for loss heads — loss vertices are applied outside (score needs
        the pre-activation input, mirroring MLN's upto=n-1 walk).
        ``ex_weight``: per-example [B] validity weight consumed only by layer
        vertices declaring CONSUMES_EXAMPLE_WEIGHT (BatchNorm excludes
        zero-weighted ParallelWrapper padding rows from batch statistics —
        same channel as MultiLayerNetwork._forward).
        ``carries``: {vertex_name: rnn carry} for the vertices in
        self._carry_vertices — when given, recurrent layer vertices run
        ``apply_seq`` from the supplied carry and the final carries are
        returned (the doTruncatedBPTT / rnnActivateUsingStoredState channel).
        ``deterministic`` (score(train=True) path): rng-drawing vertices
        (dropout / weight noise) run in eval mode while normalization keeps
        batch statistics — same contract as MultiLayerNetwork._forward.
        """
        acts: Dict[str, jax.Array] = dict(inputs)
        mask_acts: Dict[str, Any] = dict(masks or {})
        for n in self.conf.inputs:
            mask_acts.setdefault(n, None)
        new_carries = dict(carries) if carries is not None else None
        new_state = {}
        for i, name in enumerate(self.topo_order):
            v = self.rt[name]
            xs = [acts[i_] for i_ in v.inputs]
            in_masks = [mask_acts.get(i_) for i_ in v.inputs]
            rng = rngs[i] if rngs is not None else None
            if stop_at and name in stop_at:
                # loss head: keep the input activation (post-preprocessor)
                x = xs[0]
                m = in_masks[0]
                if v.pre is not None:
                    x, _ = v.pre.apply({}, {}, x, train=train, rng=None, mask=m)
                    m = v.pre.propagate_mask(m, self.vertex_types[v.inputs[0]])
                acts[name] = x
                mask_acts[name] = m
                new_state[name] = state[name]
                continue
            vtrain = train and not (
                deterministic and getattr(v.config, "uses_rng", lambda: False)())
            if v.spec.is_layer():
                x, m = xs[0], in_masks[0]
                it = self.vertex_types[v.inputs[0]] if v.inputs[0] in self.vertex_types \
                    else self.conf.input_types[v.inputs[0]]
                if v.pre is not None:
                    x, _ = v.pre.apply({}, {}, x, train=vtrain, rng=None, mask=m)
                    m = v.pre.propagate_mask(m, it)
                    it = v.input_types[0]
                p_v = params[name]
                if vtrain and v.config.weight_noise and rng is not None:
                    p_v = v.config.maybe_weight_noise(
                        p_v, vtrain, jax.random.fold_in(rng, 0x5EED)
                    )
                if new_carries is not None and name in new_carries:
                    x2 = v.config.maybe_dropout_input(x, vtrain, rng)
                    y, c = v.config.apply_seq(p_v, x2, new_carries[name], m)
                    new_carries[name] = c
                    ns = state[name]
                elif ex_weight is not None and getattr(v.config, "CONSUMES_EXAMPLE_WEIGHT", False):
                    y, ns = v.config.apply(p_v, state[name], x, train=vtrain,
                                           rng=rng, mask=m, ex_weight=ex_weight)
                else:
                    y, ns = v.config.apply(p_v, state[name], x,
                                           train=vtrain, rng=rng, mask=m)
                mask_acts[name] = v.config.propagate_mask(m, it)
            else:
                # mask_input: vertex reads the mask of a NAMED input instead
                # of its propagated one (rnn/LastTimeStepVertex.java semantics)
                ms = getattr(v.config, "mask_input", None)
                if ms is not None:
                    in_masks = [mask_acts.get(ms)] + in_masks[1:]
                y, ns = v.config.apply(params[name], state[name], xs,
                                       train=vtrain, rng=rng, masks=in_masks)
                mask_acts[name] = v.config.propagate_mask(in_masks, v.input_types)
            acts[name] = y
            new_state[name] = ns
        return acts, new_state, mask_acts, new_carries

    # -- loss --------------------------------------------------------------
    def _loss(self, params, state, inputs, labels, fmasks, lmasks, rngs, train=True,
              ex_weight=None, carries=None, deterministic=False):
        stop = set(self._loss_vertices)
        acts, new_state, mask_acts, new_carries = self._forward(
            params, state, inputs, train=train, rngs=rngs, masks=fmasks, stop_at=stop,
            ex_weight=ex_weight, carries=carries, deterministic=deterministic,
        )
        total = jnp.asarray(0.0, jnp.float32)
        for i, oname in enumerate(self.conf.outputs):
            if oname not in stop:
                continue
            v = self.rt[oname]
            y = labels[i] if isinstance(labels, (tuple, list)) else labels
            lm = None
            if lmasks is not None:
                lm = lmasks[i] if isinstance(lmasks, (tuple, list)) else lmasks
            if lm is None:
                lm = mask_acts.get(oname)
            total = total + v.config.score(params[oname], acts[oname], y, mask=lm, average=True)
        for name in self.topo_order:
            v = self.rt[name]
            total = total + v.config.regularization_penalty(params[name])
        return total, (new_state, new_carries)

    # -- jitted step -------------------------------------------------------
    def _make_step(self, with_carries: bool = False):
        from deeplearning4j_tpu.nn.step_program import StepProgram

        site = "cg.step.tbptt" if with_carries else "cg.step"
        return StepProgram(self._make_step_body(with_carries), site,
                           model=self, hits_site="cg.fit")

    def _make_step_body(self, with_carries: bool = False, grad_exchange=None):
        """The pure training-step closure. ``grad_exchange`` (a
        ``parallel.grads.GradExchange``) replaces the per-vertex update loop
        with an explicit cross-replica exchange — same contract as
        ``MultiLayerNetwork._step_body``: opt_state slot becomes
        ``(opt_state, residuals)``, loss/state are replica-means, the
        signature and return arity stay unchanged."""
        from deeplearning4j_tpu.train import resilience

        order = self.topo_order
        # divergence-guard skip_batch: the accept/reject select is traced
        # INTO the step (device-side; no extra host sync)
        guard = getattr(self, "divergence_guard", None)
        g_skip = bool(guard is not None and guard.policy == "skip_batch")
        g_limit = None if guard is None else guard.spike_limit
        # gradient-accumulation micro-batch count, baked at step-build time
        # (policy shared with MultiLayerNetwork — nn/model.py)
        from deeplearning4j_tpu.nn.model import (
            _accum_applicable, _accum_value_and_grad, _grad_accum_from_env)

        accum = _grad_accum_from_env()

        def step(params, opt_state, state, it, rng, inputs, labels, fmasks, lmasks,
                 carries, ex_weight=None):
            # python body runs once per trace → counts actual compiles
            bucketing.telemetry().record_trace(
                "cg.step", np.shape(next(iter(inputs.values()))))
            if grad_exchange is not None:
                opt_state, residuals = opt_state
            batch = (inputs, labels, fmasks, lmasks, ex_weight)
            # trace-time phase spans: fire once per compile, attributing
            # trace cost per phase (runtime attribution: DL4J_TPU_PHASE_SPANS)
            if not with_carries and _accum_applicable(accum, batch):
                # DL4J_TPU_GRAD_ACCUM: scan over micro-batches, average the
                # grads, run the (single) update/exchange below on the mean —
                # grad_exchange therefore still exchanges ONCE per step
                def make_loss_fn(mb, st, k):
                    in_i, lab_i, fm_i, lm_i, ew_i = mb
                    rngs_i = list(jax.random.split(k, len(order)))

                    def loss_fn(p):
                        return self._loss(p, st, in_i, lab_i, fm_i, lm_i,
                                          rngs_i, ex_weight=ew_i, carries=None)

                    return loss_fn

                with obs.span("phase.bwd", mode="trace"):
                    loss, new_state, grads = _accum_value_and_grad(
                        accum, params, state, batch, rng, make_loss_fn)
                new_carries = None
            else:
                rngs = list(jax.random.split(rng, len(order)))

                def loss_fn(p):
                    return self._loss(p, state, inputs, labels, fmasks, lmasks,
                                      rngs, ex_weight=ex_weight,
                                      carries=carries if with_carries else None)

                with obs.span("phase.bwd", mode="trace"):
                    ((loss, (new_state, new_carries)), grads) = jax.value_and_grad(
                        loss_fn, has_aux=True)(params)
            if grad_exchange is not None:
                loss = grad_exchange.mean_loss(loss)
                new_state = grad_exchange.mean_state(new_state)
                new_params, new_opt, new_res = grad_exchange.update(
                    grads, params, opt_state, residuals, it)
                if g_skip:
                    # loss is already the replica mean → ok is replicated
                    ok = resilience.guard_ok(loss, g_limit)
                    new_params = resilience.guard_select(ok, new_params, params)
                    new_opt = resilience.guard_select(ok, new_opt, opt_state)
                    new_res = resilience.guard_select(ok, new_res, residuals)
                    new_state = resilience.guard_select(ok, new_state, state)
                return (new_params, (new_opt, new_res), new_state,
                        new_carries, loss)
            with obs.span("phase.update", mode="trace"):
                new_params, new_opt = self._update_params(
                    params, opt_state, grads, it)
            if g_skip:
                ok = resilience.guard_ok(loss, g_limit)
                new_params = resilience.guard_select(ok, new_params, params)
                new_opt = resilience.guard_select(ok, new_opt, opt_state)
                new_state = resilience.guard_select(ok, new_state, state)
            return new_params, new_opt, new_state, new_carries, loss

        return step

    def _update_params(self, params, opt_state, grads, it):
        """Per-vertex optimizer update (normalization → updater →
        constraints), shared by the fused step body and the split-dispatch
        phase mode so both paths run identical math."""
        order = self.topo_order
        updaters = self._updaters
        new_params, new_opt = {}, {}
        for name in order:
            g = grads[name]
            if not g:
                new_params[name] = params[name]
                new_opt[name] = opt_state[name]
                continue
            cfg = self.rt[name].config
            gn = getattr(cfg, "gradient_normalization", None)
            if gn:
                g = apply_gradient_normalization(
                    gn, getattr(cfg, "gradient_normalization_threshold", 1.0), g
                )
            upd, ns = updaters[name].update(g, opt_state[name], params[name], it)
            p_new = jax.tree_util.tree_map(
                lambda p, d: p - d, params[name], upd
            )
            if getattr(cfg, "constraints", None):
                from deeplearning4j_tpu.nn.constraints import apply_constraints

                p_new = apply_constraints(cfg, p_new)
            new_params[name] = p_new
            new_opt[name] = ns
        return new_params, new_opt

    def _get_step_fn(self, with_carries: bool):
        if with_carries:
            if self._tbptt_step_fn is None:
                self._tbptt_step_fn = self._make_step(True)
            return self._tbptt_step_fn
        if self._step_fn is None:
            self._step_fn = self._make_step(False)
        return self._step_fn

    # -- chained steps (K per dispatch; mirrors MultiLayerNetwork) ---------
    def _chain_k(self) -> int:
        """Steps chained per dispatch in fit()'s hot loop (0 = per-step);
        policy shared with MultiLayerNetwork (_chain_k_from_env)."""
        from deeplearning4j_tpu.nn.model import _chain_k_from_env

        uses_rng = any(self.rt[n].config.uses_rng() for n in self.topo_order
                       if hasattr(self.rt[n].config, "uses_rng"))
        return _chain_k_from_env(uses_rng, self.num_params())

    def _make_chain_step(self):
        body = self._make_step_body()

        def chain(params, opt_state, state, it0, rng, inputs_k, labels_k):
            def scan_body(carry, inp):
                p, o, s, i = carry
                xs, ys = inp
                k = jax.random.fold_in(rng, i)
                p, o, s, _, loss = body(p, o, s, it0 + i, k, xs, ys,
                                        None, None, {})
                return (p, o, s, i + 1), loss

            (p, o, s, _), losses = jax.lax.scan(
                scan_body,
                (params, opt_state, state, jnp.asarray(0, jnp.int32)),
                (inputs_k, labels_k))
            return p, o, s, losses

        from deeplearning4j_tpu.nn.step_program import StepProgram

        # aot_wrap=False: chained dispatch bypasses the AOT warm dispatcher;
        # the StepProgram still runs the lazy cost-exemplar harvest
        return StepProgram(chain, "cg.chain", aot_wrap=False)

    def _get_chain_step(self):
        if getattr(self, "_chain_step_fn", None) is None:
            self._chain_step_fn = self._make_chain_step()
        return self._chain_step_fn

    def _initial_carries(self, batch: int) -> dict:
        if self._wrapped_rnn_vertices:
            raise NotImplementedError(
                "tBPTT / rnn_time_step cannot thread state through wrapper "
                f"RNN vertices {self._wrapped_rnn_vertices}: their inner RNN "
                "has no carry channel and would silently reset each chunk. "
                "Use the bare recurrent layer, or full-sequence calls.")
        return {
            name: self.rt[name].config.initial_carry(batch, self.dtype)
            for name in self._carry_vertices
        }

    def _time_distributed_inputs(self):
        """Input names whose InputType is recurrent — the time axis to chunk
        in tBPTT, decided from the declared types, not array rank (2-D
        integer token-id sequences are time-distributed too)."""
        return [n for n in self.conf.inputs
                if self.conf.input_types[n].kind == "recurrent"]

    # -- data normalization ------------------------------------------------
    def _norm_multi(self, v, n) -> Optional[Tuple]:
        """Normalize features/labels/masks to an n-tuple of arrays (or None)."""
        if v is None:
            return None
        if isinstance(v, (tuple, list)):
            return tuple(
                _cast_input(x, self.dtype) for x in v
            )
        return (_cast_input(v, self.dtype),) + (None,) * (n - 1) if n > 1 else (
            _cast_input(v, self.dtype),
        )

    def _as_multi_batch(self, batch):
        """Accept (x, y), (x, y, fmask, lmask) with array-or-tuple members, a
        dict, or a MultiDataSet/DataSet object — the MultiDataSet surface."""
        if hasattr(batch, "as_tuple"):
            batch = batch.as_tuple()
        if isinstance(batch, dict):
            f, l = batch["features"], batch.get("labels")
            fm, lm = batch.get("features_mask"), batch.get("labels_mask")
        else:
            f = batch[0]
            l = batch[1] if len(batch) > 1 else None
            fm = batch[2] if len(batch) > 2 else None
            lm = batch[3] if len(batch) > 3 else None
        ni, no = len(self.conf.inputs), len(self.conf.outputs)
        return (
            self._norm_multi(f, ni),
            self._norm_multi(l, no),
            self._norm_multi(fm, ni),
            self._norm_multi(lm, no),
        )

    def _input_dict(self, features: Tuple) -> Dict[str, jax.Array]:
        return dict(zip(self.conf.inputs, features))

    def _mask_dict(self, fmasks: Optional[Tuple]) -> Optional[Dict[str, Any]]:
        if fmasks is None:
            return None
        return dict(zip(self.conf.inputs, fmasks))

    # -- training ----------------------------------------------------------
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            resume_from=None):
        """Train on a MultiDataSet batch, an iterable of batches, or a
        callable returning a fresh iterable per epoch.

        ``resume_from``: a CheckpointListener directory — restore the newest
        VALID checkpoint and continue; ``epochs`` becomes the TOTAL budget
        and the interrupted epoch skips its already-consumed batches (same
        contract as MultiLayerNetwork.fit; docs/ROBUSTNESS.md)."""
        from deeplearning4j_tpu.train import resilience
        from deeplearning4j_tpu.train.listeners import close_listeners

        if self.params is None:
            self.init()
        resume_skip = 0
        if resume_from is not None:
            if resilience.resume(self, resume_from) is not None:
                resume_skip = int(getattr(self, "batch_in_epoch", 0))
                epochs = max(epochs - self.epoch, 0)
        import os as _os

        if _os.environ.get("DL4J_TPU_TUNE"):
            # persisted tuner winner, applied before chain/warm/step-build
            # read their envs (same hook as MultiLayerNetwork.fit)
            from deeplearning4j_tpu import tune as _tune

            _tune.maybe_apply(self, "fit")
        guard = getattr(self, "divergence_guard", None)
        if aot.enabled():
            # time-to-first-step becomes a warm-path number: compile (or
            # reuse a bundle-restored executable for) the first batch's step
            # signature before the epoch loop dispatches. Mirrors the
            # per-epoch tbptt/chain gating below for epoch 0.
            _tbptt0 = (self.conf.backprop_type == "tbptt"
                       and bool(self._time_distributed_inputs()))
            _chain0 = (self._chain_k()
                       if not (self.listeners or _tbptt0) and guard is None
                       else 0)
            if not _tbptt0 and _chain0 <= 1:
                aot.warm_fit(self, data, batch_size)
        try:
            for _ in range(epochs):
                skip_n, resume_skip = resume_skip, 0
                self.batch_in_epoch = skip_n
                for l in self.listeners:
                    l.on_epoch_start(self, self.epoch)
                source = data() if callable(data) else data
                tbptt = (self.conf.backprop_type == "tbptt"
                         and bool(self._time_distributed_inputs()))
                chain_k = (self._chain_k()
                           if not (self.listeners or tbptt) and guard is None
                           else 0)
                buf: list = []
                # pad every batch (incl. the partial tail) to ONE row count
                # with a uniform ew/lmask calling convention → one compiled
                # step (mirrors MultiLayerNetwork.fit); the chained path
                # needs bare (f, l) batches, so it opts out
                pad_target = (self._fit_pad_target_multi(source, batch_size)
                              if chain_k <= 1 and not tbptt
                              and bucketing.bucketing_enabled() else None)

                def flush(full: bool):
                    # full K-groups go out as ONE dispatch; tails use the
                    # per-step path (a different K = a fresh compile)
                    if not buf:
                        return
                    with obs.span("cg.fit_batch", batches=len(buf)):
                        if full and len(buf) > 1:
                            self._fit_chained(buf)
                        else:
                            for bf, bl in buf:
                                self.fit_batch((bf, bl, None, None))
                    buf.clear()

                def batches():
                    it = self._iter_multi(source, batch_size)
                    # resume: already-consumed batches of the interrupted
                    # epoch are skipped HERE, without touching the RNG (the
                    # restored key is already past them)
                    for _ in range(skip_n):
                        if next(it, None) is None:
                            return
                    for f, l, fm, lm in it:
                        # real-row count taken HERE, before padding, so the
                        # fit loop never syncs ew back from device to learn it
                        n = len(f[0])
                        if pad_target is not None:
                            yield bucketing.pad_fit_multi(
                                f, l, fm, lm, pad_target, site="cg.fit") + (n,)
                        else:
                            yield (f, l, fm, lm, None, n)

                stream = batches()
                from deeplearning4j_tpu.nn.model import (
                    _batch_sig, _device_prefetch_enabled)
                if _device_prefetch_enabled():
                    # overlap next batch's host→device transfer with this
                    # step's compute (double buffering); AFTER padding,
                    # which is host-side
                    from deeplearning4j_tpu.datasets.iterator import prefetch_to_device

                    stream = prefetch_to_device(stream)
                for f, l, fm, lm, ew, n_real in stream:
                    batch = (f, l, fm, lm)
                    chainable = (
                        chain_k > 1 and fm is None and lm is None
                        and l is not None and all(y is not None for y in l)
                        and (not buf or _batch_sig(f + l)
                             == _batch_sig(buf[0][0] + buf[0][1]))
                    )
                    if chainable:
                        buf.append((f, l))
                        self.batch_in_epoch += 1
                        if len(buf) == chain_k:
                            flush(True)
                        continue
                    flush(False)
                    with obs.span("cg.fit_batch"):
                        if tbptt:
                            score = self._fit_tbptt(*batch)
                        else:
                            score = self.fit_batch(batch, ew=ew)
                    self.batch_in_epoch += 1
                    if guard is not None:
                        guard.observe(self, score)
                    if self.listeners:
                        # n_real came from the pre-padding host side of the
                        # stream
                        score = float(score)  # graftlint: disable=host-sync
                        resilience.note_score(score)
                        for l in self.listeners:
                            l.iteration_done(self, self.iteration, score, n_real)
                flush(False)
                if guard is not None:
                    guard.flush(self)
                for l in self.listeners:
                    l.on_epoch_end(self, self.epoch)
                self.epoch += 1
        finally:
            # a run ending inside a ProfilerListener [start, stop) window
            # (normally or via an exception/chaos preempt) must not leak an
            # open jax.profiler trace
            close_listeners(self.listeners)
        return self

    def _is_single_multibatch(self, data) -> bool:
        """True when ``data`` is ONE in-memory MultiDataSet-like batch (not an
        iterable of batches). Disambiguation uses the model's input arity: a
        single batch's features must be one array (1-input nets) or a tuple
        of exactly len(inputs) arrays."""
        def _is_arr(v):
            return isinstance(v, (np.ndarray, jax.Array)) or hasattr(v, "__array__")

        ni = len(self.conf.inputs)

        def _features_like(f):
            if _is_arr(f):
                return ni == 1
            return (
                isinstance(f, (tuple, list))
                and len(f) == ni
                and all(_is_arr(e) for e in f)
            )

        return (isinstance(data, dict)
                or (isinstance(data, (tuple, list)) and 2 <= len(data) <= 4
                    and _features_like(data[0])))

    def _fit_pad_target_multi(self, data, batch_size) -> Optional[int]:
        """Uniform per-batch row count for fit() over one in-memory batch
        source, or None (mirrors model._fit_pad_target: only worth padding
        when minibatching leaves a partial tail that would otherwise trace a
        second training executable)."""
        if batch_size is None:
            return None
        if hasattr(data, "as_tuple"):
            data = data.as_tuple()
        if self._is_single_multibatch(data):
            f, _, _, _ = self._as_multi_batch(data)
            n = f[0].shape[0]
            if n > batch_size and n % batch_size != 0:
                return batch_size
        return None

    def _iter_multi(self, data, batch_size):
        """Yield MultiDataSet batches. A bare (features, labels) pair of
        arrays/tuples is minibatched when batch_size is given."""
        if hasattr(data, "as_tuple"):  # datasets.DataSet / MultiDataSet
            data = data.as_tuple()

        if self._is_single_multibatch(data):
            f, l, fm, lm = self._as_multi_batch(data)
            n = f[0].shape[0]
            if batch_size is None or batch_size >= n:
                yield (f, l, fm, lm)
                return
            sl_t = lambda t, s: tuple(x[s] if x is not None else None for x in t) if t else None
            for i in range(0, n, batch_size):
                s = slice(i, min(i + batch_size, n))
                yield (sl_t(f, s), sl_t(l, s), sl_t(fm, s), sl_t(lm, s))
            return
        for b in data:
            yield self._as_multi_batch(b)

    def fit_batch(self, batch, ew=None):
        """One jitted step on one (already normalized or raw) batch.
        ``ew``: optional per-example validity weight (ParallelWrapper
        padding) consumed by batch-coupled layer vertices — see _forward."""
        if isinstance(batch, tuple) and len(batch) == 4 and isinstance(batch[0], tuple) \
                and all(x is None or isinstance(x, (jax.Array, np.ndarray))
                        for x in batch[0]):
            f, l, fm, lm = batch
        else:
            f, l, fm, lm = self._as_multi_batch(batch)
        from deeplearning4j_tpu.train import resilience

        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_preempt(self.iteration)
            chaos.maybe_slow(self.iteration)
            f = chaos.maybe_nan_batch(self.iteration, f)
        step = self._get_step_fn(False)
        # dispatch() runs the step, then the retrace-guard check the program
        # owns: traces land at cg.step (inside the jitted body), bucket
        # traffic lands at cg.fit (pad_fit_multi) — the guard joins the two
        self.params, self.opt_state, self.state, _, loss = step.dispatch(
            self.params, self.opt_state, self.state,
            jnp.asarray(self.iteration, jnp.int32), self._next_rng(),
            self._input_dict(f), l, self._mask_dict(fm), lm, {},
            ex_weight=jnp.asarray(ew, self.dtype) if ew is not None else None,
        )
        self.iteration += 1
        return loss

    def _fit_tbptt(self, f, l, fm, lm):
        """Truncated BPTT over the DAG (ComputationGraph.java:950,1179
        doTruncatedBPTT): chunk the time axis of every recurrent input (and
        time-distributed labels/masks), carry RNN-vertex state across chunks
        with stopped gradients. Static ([B,F]) inputs are re-fed whole to
        every chunk — the DuplicateToTimeSeriesVertex use case."""
        from deeplearning4j_tpu.train import resilience

        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_preempt(self.iteration)
            chaos.maybe_slow(self.iteration)
        step = self._get_step_fn(True)
        td_inputs = set(self._time_distributed_inputs())
        T = max(x.shape[1] for n, x in zip(self.conf.inputs, f) if n in td_inputs)
        L = self.conf.tbptt_fwd_length
        B = f[0].shape[0]
        carries = self._initial_carries(B)

        slice_t = lambda x, sl, kind: _tbptt_slice_t(x, sl, T, kind)
        total, nchunks = 0.0, 0
        for t0 in range(0, T, L):
            sl = slice(t0, min(t0 + L, T))
            fc = tuple(
                _cast_input(slice_t(x, sl, "feat_td" if n in td_inputs else "feat"),
                            self.dtype)
                for n, x in zip(self.conf.inputs, f))
            lc = tuple(_cast_labels(slice_t(y, sl, "label"), self.dtype)
                       for y in l) if l is not None else None
            fmc = tuple(jnp.asarray(slice_t(m, sl, "mask"), self.dtype)
                        if m is not None else None
                        for m in fm) if fm is not None else None
            lmc = tuple(jnp.asarray(slice_t(m, sl, "mask"), self.dtype)
                        if m is not None else None
                        for m in lm) if lm is not None else None
            self.params, self.opt_state, self.state, carries, loss = step(
                self.params, self.opt_state, self.state,
                jnp.asarray(self.iteration, jnp.int32), self._next_rng(),
                self._input_dict(fc), lc, self._mask_dict(fmc), lmc, carries,
            )
            # truncation is structural: each chunk is its own jitted step, so
            # the concrete carry arrays carry values, never gradients
            total = total + loss
            nchunks += 1
            self.iteration += 1
        return total / max(nchunks, 1)

    def _fit_chained(self, buf) -> None:
        """One dispatch covering len(buf) train steps (lax.scan of the
        step body over stacked batches; mirrors MultiLayerNetwork)."""
        chain = self._get_chain_step()
        ni, no = len(self.conf.inputs), len(self.conf.outputs)
        fk = tuple(jnp.stack([b[0][i] for b in buf]) for i in range(ni))
        lk = tuple(jnp.stack([b[1][i] for b in buf]) for i in range(no))
        self.params, self.opt_state, self.state, _ = chain(
            self.params, self.opt_state, self.state,
            jnp.asarray(self.iteration, jnp.int32), self._next_rng(),
            self._input_dict(fk), lk)
        self.iteration += len(buf)

    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    # -- inference ---------------------------------------------------------
    def _get_output_fn(self):
        """The jitted inference entry point, AOT-wrapped so warmup
        (``nn/aot.py``) can pre-compile every ladder bucket and bundle
        restore can install persisted executables."""
        if self._output_fn is None:
            def fwd(params, state, inputs, masks):
                # python body runs once per trace → counts actual compiles
                bucketing.telemetry().record_trace(
                    "cg.output", np.shape(next(iter(inputs.values()))))
                acts, _, _, _ = self._forward(params, state, inputs, train=False,
                                              rngs=None, masks=masks)
                return tuple(acts[o] for o in self.conf.outputs)

            from deeplearning4j_tpu.nn.step_program import StepProgram

            self._output_fn = StepProgram(
                fwd, "cg.output", model=self, donate_argnums=())
        return self._output_fn

    def output(self, *xs, fmasks=None):
        """Outputs of all output vertices (ComputationGraph.output:1754).
        Returns a single array when the graph has one output.

        Batch rows are padded up to the shared bucket ladder before dispatch
        (and sliced back off) so mixed caller batch sizes share one compiled
        executable per bucket; skipped for graphs with Stack/Unstack
        vertices, whose batch-axis arithmetic padding would corrupt.
        Disable via DL4J_TPU_BUCKETING=0."""
        if len(xs) == 1 and isinstance(xs[0], (tuple, list)):
            xs = tuple(xs[0])
        feats = tuple(_cast_input(x, self.dtype) for x in xs)
        fm = self._norm_multi(fmasks, len(self.conf.inputs)) if fmasks is not None else None
        self._get_output_fn()
        n = feats[0].shape[0] if feats else 0
        with obs.span("cg.output"):
            if (bucketing.bucketing_enabled() and n > 0
                    and not self._has_batch_vertices):
                target = bucketing.bucket_size(n)
                bucketing.telemetry().record_hit("cg.output", n, target)
                if target > n:
                    feats = tuple(bucketing.pad_rows_zero(x, target) for x in feats)
                    if fm is not None:
                        fm = tuple(bucketing.pad_rows_zero(m, target)
                                   if m is not None else None for m in fm)
                    outs = self._output_fn.dispatch(
                        self.params, self.state, self._input_dict(feats),
                        self._mask_dict(fm))
                    outs = tuple(bucketing.unpad(o, n) for o in outs)
                    return outs[0] if len(outs) == 1 else outs
            outs = self._output_fn.dispatch(
                self.params, self.state, self._input_dict(feats),
                self._mask_dict(fm))
        return outs[0] if len(outs) == 1 else outs

    # -- streaming RNN inference (ComputationGraph.rnnTimeStep:2718) -------
    def rnn_time_step(self, *xs):
        """Feed one or more timesteps per recurrent input, carrying RNN-vertex
        state between calls (rnnTimeStep:2718-2800 /
        rnnActivateUsingStoredState:1334). A 2-D array for a recurrent input
        means a single timestep; outputs are squeezed back to 2-D in that
        case. Static inputs pass [B,F] unchanged."""
        if len(xs) == 1 and isinstance(xs[0], (tuple, list)):
            xs = tuple(xs[0])
        feats, squeeze = [], False
        for name, x in zip(self.conf.inputs, xs):
            x = _cast_input(x, self.dtype)
            if self.conf.input_types[name].kind == "recurrent":
                if jnp.issubdtype(x.dtype, jnp.integer):
                    # token-id stream: full input is [B,T]; [B] = one step
                    if x.ndim == 1:
                        x = x[:, None]
                        squeeze = True
                elif x.ndim == 2:
                    x = x[:, None, :]
                    squeeze = True
            feats.append(x)
        B = feats[0].shape[0]
        leaves = (jax.tree_util.tree_leaves(self._rnn_carries)
                  if self._rnn_carries is not None else [])
        if self._rnn_carries is None or (leaves and leaves[0].shape[0] != B):
            self._rnn_carries = self._initial_carries(B)
        acts, _, _, self._rnn_carries = self._forward(
            self.params, self.state, self._input_dict(tuple(feats)),
            train=False, rngs=None, carries=self._rnn_carries)
        outs = tuple(
            a[:, 0, :] if squeeze and a.ndim == 3 and a.shape[1] == 1 else a
            for a in (acts[o] for o in self.conf.outputs)
        )
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        self._rnn_carries = None

    def score(self, batch, train: bool = False) -> float:
        """Average loss on a batch. ``train=True`` scores with training-mode
        statistics (BatchNorm uses the batch's own mean/var, not the running
        estimates) while dropout / weight noise stay disabled — deterministic;
        see MultiLayerNetwork.score."""
        f, l, fm, lm = self._as_multi_batch(batch)
        loss, _ = self._loss(self.params, self.state, self._input_dict(f), l,
                             self._mask_dict(fm), lm, rngs=None, train=train,
                             deterministic=True)
        return float(loss)

    def evaluate(self, data, batch_size: Optional[int] = None, top_n: int = 1):
        """Single-output classification evaluation."""
        from deeplearning4j_tpu.eval import Evaluation

        ev = Evaluation(top_n=top_n)
        for f, l, fm, lm in self._iter_multi(data, batch_size):
            preds = self.output(*f, fmasks=fm)
            y = l[0] if isinstance(l, tuple) else l
            m = lm[0] if isinstance(lm, (tuple, list)) and lm else None
            ev.eval(np.asarray(y), np.asarray(preds), mask=np.asarray(m) if m is not None else None)
        return ev

    # -- misc --------------------------------------------------------------
    def clone(self) -> "ComputationGraph":
        m = ComputationGraph(self.conf)
        if self.params is not None:
            m.init()
            copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
            m.params = copy(self.params)
            m.state = copy(self.state)
            m.opt_state = copy(self.opt_state)
            m.iteration = self.iteration
            m.epoch = self.epoch
        return m

    def summary(self) -> str:
        lines = [f"{'name':<24} {'type':<24} {'inputs':<30} {'output':<22} {'params':<10}"]
        for name in self.topo_order:
            v = self.rt[name]
            tname = getattr(v.config, "_type_name", getattr(v.config, "_vtype_name", "?"))
            n = (
                sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params[name]))
                if self.params is not None else "?"
            )
            lines.append(
                f"{name:<24} {tname:<24} {','.join(v.inputs)[:30]:<30} "
                f"{str(v.out_type.batch_shape())[:22]:<22} {n:<10}"
            )
        lines.append(f"Total params: {self.num_params() if self.params is not None else '?'}")
        return "\n".join(lines)
