"""Parameter constraints, applied INSIDE the jitted step after each update.

Parity: nn/conf/constraint/ (MaxNormConstraint, MinMaxNormConstraint,
UnitNormConstraint, NonNegativeConstraint; BaseConstraint applies per
output-unit norms over the non-output axes, weight params only unless
configured otherwise). TPU-first: the constraint is a pure tensor->tensor
projection fused by XLA into the same executable as the update — zero
extra dispatches, unlike the reference's post-step host call.

Specs are JSON-friendly dicts on ``LayerConfig.constraints``:
    {"type": "max_norm", "max_norm": 2.0}
    {"type": "min_max_norm", "min_norm": 0.5, "max_norm": 2.0, "rate": 1.0}
    {"type": "unit_norm"}
    {"type": "non_negative"}
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

_EPS = 1e-9


def _unit_axes(w: jax.Array) -> tuple:
    """Norm-reduction axes: everything except the last (output-unit) axis,
    matching the reference's per-output-neuron column norms."""
    return tuple(range(w.ndim - 1)) if w.ndim > 1 else (0,)


def _norms(w: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.sum(w * w, axis=_unit_axes(w), keepdims=True) + _EPS)


def max_norm(w: jax.Array, max_norm_v: float) -> jax.Array:
    n = _norms(w)
    return w * jnp.minimum(n, max_norm_v) / n


def min_max_norm(w: jax.Array, min_v: float, max_v: float, rate: float = 1.0) -> jax.Array:
    n = _norms(w)
    clipped = jnp.clip(n, min_v, max_v)
    target = rate * clipped + (1.0 - rate) * n
    return w * target / n


def unit_norm(w: jax.Array) -> jax.Array:
    return w / _norms(w)


def non_negative(w: jax.Array) -> jax.Array:
    return jnp.maximum(w, 0.0)


def _apply_one(spec: Dict[str, Any], w: jax.Array) -> jax.Array:
    t = spec.get("type")
    if t == "max_norm":
        return max_norm(w, float(spec.get("max_norm", 2.0)))
    if t == "min_max_norm":
        return min_max_norm(w, float(spec.get("min_norm", 0.0)),
                            float(spec.get("max_norm", 2.0)),
                            float(spec.get("rate", 1.0)))
    if t == "unit_norm":
        return unit_norm(w)
    if t == "non_negative":
        return non_negative(w)
    raise ValueError(f"unknown constraint type {t!r}")


def apply_constraints(layer, params):
    """Project a layer's params per its ``constraints`` specs. Weight-class
    params only unless a spec sets ``apply_to_biases``; recurses into nested
    dicts (wrapper layers)."""
    specs = tuple(getattr(layer, "constraints", ()) or ())
    if not specs or not params:
        return params
    bias_names = layer.BIAS_PARAM_NAMES

    def visit(p):
        out = {}
        for name, v in p.items():
            if isinstance(v, dict):
                out[name] = visit(v)
                continue
            new_v = v
            for spec in specs:
                if name in bias_names and not spec.get("apply_to_biases", False):
                    continue
                new_v = _apply_one(spec, new_v)
            out[name] = new_v
        return out

    return visit(params)
