"""One compiled step program: the single owner of step wiring policy.

Every training/inference entry point in the framework used to hand-roll the
same five-line stanza — ``jax.jit(body, donate_argnums=(0, 1, 2))``,
``aot.wrap`` at a site name, a ``retrace_guard.check_if_enabled`` after each
dispatch, a grad-accumulation scan spliced into the body, and an exemplar
harvest for the cost model. MultiLayerNetwork, ComputationGraph,
DataParallelStep, the gpipe stages and the serve/decode executors each
carried their own copy, and the copies drifted (ISSUE 13). This module is
now the only place that wiring exists:

- :class:`StepProgram` — one compiled entry point: trace/donate policy,
  AOT-warm dispatch (``nn/aot.py``), retrace-guard hookup
  (``analysis/retrace_guard.py``) and cost-exemplar harvest, behind a
  callable that quacks like the ``AotFunction`` it wraps.
- the **micro-batching policy** shared by every step builder:
  :func:`grad_accum_from_env` / :func:`accum_applicable` /
  :func:`accum_value_and_grad` (the lax.scan gradient accumulation INSIDE
  the donated step) and :func:`chain_k_from_env` (K steps per dispatch).
- the **mesh-shape policy**: :func:`mesh_shape_from_env` resolves the
  ``(data, tensor, stage)`` axes of the named-mesh step
  (``parallel/mesh_step.py``) from the ``DL4J_TPU_MESH_*`` knobs that
  ``tune/knobs.py`` registers for the successive-halving search.

A graftlint rule (``step-wiring``, ``analysis/rules.py``) forbids new
direct ``jax.jit(..., donate_argnums=...)`` step construction in ``nn/``
and ``parallel/`` outside this module, so the wiring cannot fork a sixth
time. See docs/PARALLELISM.md.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.analysis import donation_guard, retrace_guard

__all__ = [
    "CHAIN_AUTO_PARAM_LIMIT",
    "StepProgram",
    "accum_applicable",
    "accum_value_and_grad",
    "chain_k_from_env",
    "grad_accum_from_env",
    "mesh_shape_from_env",
]


class StepProgram:
    """One compiled step/output program and its dispatch policy.

    Owns, in exactly one place, what every model/parallel step used to wire
    by hand:

    - **trace/donate**: ``body`` is jitted with ``donate_argnums`` (the
      params/opt/state carry donates by default, so the step updates in
      place buffer-wise);
    - **AOT**: the jitted function is registered at ``site`` on ``model``'s
      AOT registry (``aot.wrap``) so ladder warmup, bundle persistence and
      warm dispatch all find it — ``aot_wrap=False`` opts out for entry
      points that must bypass the AOT dispatcher (chained steps, phase
      profiling) while keeping the lazy cost-exemplar harvest;
    - **retrace guard**: :meth:`dispatch` runs the call followed by the
      guard check for ``guard_site`` (defaults to ``site``) with the
      configured ``hits_site``/``extra_allowed``, so callers can't forget
      the check or disagree on the budget.

    ``wrap_body`` (e.g. a ``shard_map`` closure for the explicit DP
    exchange) transforms the body before jit. Everything not implemented
    here delegates to the wrapped callable, so existing code that expects
    an ``AotFunction`` (``warm``/``compiled_count``/``signatures``/
    ``install``/``lower``) keeps working unchanged.
    """

    def __init__(self, body: Callable, site: str, *, model=None,
                 donate_argnums: Tuple[int, ...] = (0, 1, 2),
                 static_argnums: Optional[Tuple[int, ...]] = None,
                 wrap_body: Optional[Callable[[Callable], Callable]] = None,
                 aot_wrap: bool = True,
                 guard_site: Optional[str] = None,
                 hits_site: Optional[str] = None,
                 extra_allowed: int = 0):
        from deeplearning4j_tpu.nn import aot

        self.site = site
        self.guard_site = guard_site or site
        self.hits_site = hits_site
        self.extra_allowed = extra_allowed
        self.donate_argnums = tuple(donate_argnums)
        fn = body if wrap_body is None else wrap_body(body)
        kwargs: dict = {"donate_argnums": self.donate_argnums}
        if static_argnums is not None:
            kwargs["static_argnums"] = tuple(static_argnums)
        jitted = jax.jit(fn, **kwargs)
        self._aot = bool(aot_wrap)
        self._fn = (aot.wrap(jitted, site, model=model,
                             static_argnums=kwargs.get("static_argnums"))
                    if aot_wrap else jitted)

    # -- dispatch ----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        if not self._aot:
            # plain-jit programs (chained dispatch, phase fns) still feed
            # the cost model: aval capture only on the (rare) compile path
            from deeplearning4j_tpu.obs import profile as _profile

            if _profile.wants_exemplar(self.site):
                _profile.note_exemplar(self.site, self._fn, args, kwargs)
        if self.donate_argnums and donation_guard.enabled():
            # debug mode: poison donated inputs the backend left alive so a
            # use-after-donate the static rule missed fails loudly on CPU too
            donation_guard.check_after_dispatch(
                self.site, args, self.donate_argnums, out)
        return out

    def dispatch(self, *args, **kwargs):
        """Call, then run the retrace-guard check this program owns."""
        out = self(*args, **kwargs)
        self.guard()
        return out

    def guard(self):
        """The post-dispatch retrace-guard check (no-op unless enabled)."""
        retrace_guard.check_if_enabled(
            self.guard_site, hits_site=self.hits_site,
            extra_allowed=self.extra_allowed)

    # -- AotFunction parity ------------------------------------------------
    def warm(self, *args, **kwargs):
        return self._fn.warm(*args, **kwargs)

    @property
    def compiled_count(self) -> int:
        return getattr(self._fn, "compiled_count", 0)

    def __getattr__(self, name: str):
        # anything else (signatures/install/lower/_compiled/...) is the
        # wrapped callable's business
        return getattr(self.__dict__["_fn"], name)


# ---------------------------------------------------------------------------
# Micro-batching policy (shared by MLN / CG / DP / mesh step builders)
# ---------------------------------------------------------------------------

# Above this parameter count, "auto" never chains: big models are
# compute-bound, so amortizing dispatch buys nothing and the stacked
# [K, B, ...] batch just costs memory.
CHAIN_AUTO_PARAM_LIMIT = 2_000_000

_CHAIN_RNG_WARNED = False


def chain_k_from_env(uses_rng: bool, n_params: int) -> int:
    """Shared chained-fit gate for MultiLayerNetwork and ComputationGraph:
    DL4J_TPU_CHAIN_STEPS forces a count (0 disables); "auto" chains 8 only
    for rng-free models small enough to be dispatch-bound. Phase-span
    profiling (DL4J_TPU_PHASE_SPANS=1) disables auto-chaining: its whole
    point is per-phase dispatch, which a K-step chain would hide — an
    explicit DL4J_TPU_CHAIN_STEPS count still wins."""
    import os as _os

    env = _os.environ.get("DL4J_TPU_CHAIN_STEPS", "auto")
    if env == "auto" and obs.phase_spans_enabled():
        return 0
    if env != "auto":
        try:
            k = max(int(env), 0)
        except ValueError:
            return 0
        if k > 1 and uses_rng:
            global _CHAIN_RNG_WARNED
            if not _CHAIN_RNG_WARNED:
                _CHAIN_RNG_WARNED = True
                import warnings

                warnings.warn(
                    f"DL4J_TPU_CHAIN_STEPS={env} forces chained dispatch on a "
                    "model that draws randomness (dropout/weight noise): "
                    "per-step rngs derive as fold_in(rng, i) inside the "
                    "chain, a different-but-equivalent stream from the "
                    "per-step path, so losses will not be bitwise "
                    "reproducible against unchained runs.")
        return k
    return 8 if (not uses_rng and n_params < CHAIN_AUTO_PARAM_LIMIT) else 0


_GRAD_ACCUM_WARNED = False


def grad_accum_from_env() -> int:
    """Micro-batch count for gradient accumulation inside the jitted step
    (DL4J_TPU_GRAD_ACCUM, default 1 = off). Shared by MultiLayerNetwork and
    ComputationGraph; read at step-BUILD time, so a change after the first
    compile needs ``_clear_compiled()`` (the tuner's trial subprocesses get
    a fresh build for free). See docs/TUNING.md."""
    import os as _os

    env = _os.environ.get("DL4J_TPU_GRAD_ACCUM", "1")
    try:
        return max(int(env), 1)
    except ValueError:
        return 1


def accum_applicable(accum: int, batch) -> bool:
    """Trace-time gate for the accumulated step: every batch-major leaf must
    share one leading row count divisible by ``accum`` (micro-batches must be
    equal-sized for the mean-of-means loss to equal the full-batch mean).
    Falls back to the un-accumulated step otherwise — silently for accum<=1,
    with a one-shot warning when the knob is set but the batch doesn't fit."""
    if accum <= 1:
        return False
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves or leaves[0].ndim == 0:
        return False
    b = leaves[0].shape[0]
    if b < accum or b % accum != 0 or not all(
            l.ndim >= 1 and l.shape[0] == b for l in leaves):
        # warn-once flag: once-per-trace IS the wanted semantic here, and
        # the boolean never feeds the traced computation
        global _GRAD_ACCUM_WARNED  # graftlint: disable=jit-purity
        if not _GRAD_ACCUM_WARNED:
            _GRAD_ACCUM_WARNED = True
            import warnings

            warnings.warn(
                f"DL4J_TPU_GRAD_ACCUM={accum} does not divide the batch "
                f"(leading dims {[l.shape[0] for l in leaves[:4]]}); this "
                "step runs un-accumulated.")
        return False
    return True


def accum_value_and_grad(accum, params, state, batch, rng, make_loss_fn):
    """Gradient accumulation: one ``lax.scan`` over ``accum`` equal
    micro-batches INSIDE the donated step executable. Each micro-batch runs
    forward + backward at 1/accum the activation footprint (the scan re-uses
    one micro-batch's live activations — this is the knob that unlocks
    batches beyond HBM); gradients accumulate in a carry and are averaged
    once, so the single optimizer update downstream sees exactly the
    mean-of-micro-means gradient. For equal micro-batches with no masks that
    equals the full-batch mean bitwise up to fp summation order (the parity
    test pins fp32 tolerance); per-micro-batch means under row masks follow
    the same mean-of-means contract the DP replica exchange already uses.

    ``batch`` is a pytree of batch-major arrays (None leaves allowed).
    ``make_loss_fn(micro_batch, state, rng_i)`` returns the per-micro-batch
    ``loss_fn(params) -> (loss, (new_state, aux))``. Mutable layer state
    (BatchNorm running stats) threads micro-batch to micro-batch, matching
    what sequential small batches would do. Per-micro rngs derive as
    ``fold_in(rng, i)`` — a different-but-equivalent stream from the
    un-accumulated step for models that draw randomness (same caveat as
    chained dispatch)."""
    micro = jax.tree_util.tree_map(
        lambda t: t.reshape((accum, t.shape[0] // accum) + t.shape[1:]),
        batch)

    def body(carry, mb):
        st, g_acc, loss_acc, i = carry
        loss_fn = make_loss_fn(mb, st, jax.random.fold_in(rng, i))
        (loss_i, (st_i, _)), g_i = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        g_acc = jax.tree_util.tree_map(lambda a, g: a + g, g_acc, g_i)
        return (st_i, g_acc, loss_acc + loss_i, i + 1), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (new_state, g_sum, loss_sum, _), _ = jax.lax.scan(
        body,
        (state, zeros, jnp.asarray(0.0, jnp.float32),
         jnp.asarray(0, jnp.int32)),
        micro)
    inv = 1.0 / accum
    grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
    return loss_sum * inv, new_state, grads


# ---------------------------------------------------------------------------
# Mesh-shape policy (the (d, t, s) knobs of the named-mesh step)
# ---------------------------------------------------------------------------


def _axis_env(name: str) -> int:
    import os as _os

    raw = _os.environ.get(name, "0")
    try:
        return max(int(raw), 0)
    except ValueError:
        return 0


def mesh_shape_from_env(n_devices: int) -> Tuple[int, int, int]:
    """Resolve the named-mesh step's ``(data, tensor, stage)`` shape from
    the ``DL4J_TPU_MESH_DATA`` / ``DL4J_TPU_MESH_MODEL`` /
    ``DL4J_TPU_MESH_PIPE`` knobs (``tune/knobs.py``; 0/unset = auto).

    Auto policy: unset tensor/stage axes default to 1 and the unset data
    axis absorbs every remaining device — so with no knobs set this is pure
    DP over all devices, the baseline the MULTICHIP bench gate compares
    tuned shapes against. A shape whose product does not divide
    ``n_devices`` is a configuration error and raises (the knob domains the
    tuner searches are derived from the local device count precisely so its
    trials never land here)."""
    t = _axis_env("DL4J_TPU_MESH_MODEL") or 1
    s = _axis_env("DL4J_TPU_MESH_PIPE") or 1
    d = _axis_env("DL4J_TPU_MESH_DATA")
    if d == 0:
        if n_devices % (t * s):
            raise ValueError(
                f"mesh axes model={t} x pipe={s} do not divide "
                f"{n_devices} devices")
        d = n_devices // (t * s)
    if d * t * s != n_devices:
        raise ValueError(
            f"mesh shape (d={d}, t={t}, s={s}) does not cover "
            f"{n_devices} devices")
    return d, t, s
