"""Bucketed KV-cache decode engine: the autoregressive serving step.

Training and batch inference run whole sequences through ``mln.output``;
autoregressive generation is a different dispatch shape entirely — one new
token (or one prefill chunk) per step against an ever-growing key/value
history. :class:`DecodeProgram` compiles that step ONCE per bucket triple
and keeps the history in a device-resident cache, so steady-state decode
never re-runs the prompt and never compiles:

- **Unified step.** One jitted function serves both phases: prefill is the
  step at chunk width ``Tc`` (a bucket of ``prefill_chunk``), decode is the
  same step at ``Tc = 1``. The step embeds the chunk, walks the transformer
  stack through the layers' ``decode_apply`` paths (single-query attention
  against the cache — ops/flash_attention.decode_attention), scatters the
  chunk's k/v into the cache, and returns next-token logits + greedy ids.

- **Paged cache on the bucket ladder.** The cache is a page pool
  ``[P, page_tokens, H, D]`` per transformer block plus a host-managed page
  table: each stream owns an ordered page list, and a dispatch passes a
  ``[B_bucket, NP_bucket]`` int32 table slice. Every dispatch-visible shape
  — batch rows, chunk width, table width — lives on the shared bucket
  ladder (utils/bucketing.py), so the WHOLE executable set is enumerable
  and AOT-warm at registration (``warm``; the zero-compile serving gate).
  Page 0 is a scratch page: padded batch rows and padded chunk slots direct
  their writes there, so padding never touches a real stream's history.

- **Contiguous mode** (``paged=False``) keeps one ``[S+1, L+1, H, D]``
  strip per slot (row S / column L are the padding scratch) — same step
  math, executables keyed by batch bucket only. It is the parity oracle
  for the paged layout (tests/test_generate.py) and the layout of choice
  when capacity is small enough that paging buys nothing.

- **Bit-exactness.** Greedy decode through this program is bit-exact
  batched vs unbatched: rows are independent, and every padded/masked
  cache position contributes an exact-zero softmax weight (see
  decode_attention) — trailing zero terms that leave real rows' reductions
  unchanged. The serving tier's batched==solo guarantee (PR 8) therefore
  extends to token streams.

The program mutates no model state: ``model.params``/``model.state`` pass
through the jitted step unchanged; only the cache pools (donated) evolve.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn import aot
from deeplearning4j_tpu.utils import bucketing

__all__ = ["DecodeProgram"]

SITE = "decode.step"


# ---------------------------------------------------------------------------
# Cache views: what a layer's decode_apply sees (paging stays out of layers)
# ---------------------------------------------------------------------------


class _PagedView:
    """One transformer block's window onto the page pool for one dispatch.

    ``pool`` {"k","v"}: [P, page_tokens, H, D]; ``table`` [B, NP] int32
    (page ids per stream, in order — gathered index g along the flattened
    span IS absolute position g); ``positions`` [B, Tc]; ``valid`` [B, Tc]
    marks real chunk slots (padding writes land on scratch page 0)."""

    def __init__(self, pool, table, positions, valid, page_tokens: int):
        self.pool = pool
        self._table = table
        self._pos = positions
        self._valid = valid
        self._pg = page_tokens

    def append(self, k_new, v_new):
        npages = self._table.shape[1]
        slot = jnp.clip(self._pos // self._pg, 0, npages - 1)
        page = jnp.take_along_axis(self._table, slot, axis=1)     # [B, Tc]
        off = self._pos % self._pg
        page = jnp.where(self._valid, page, 0)   # padding -> scratch page
        off = jnp.where(self._valid, off, 0)
        dt = self.pool["k"].dtype
        self.pool = {
            "k": self.pool["k"].at[page, off].set(k_new.astype(dt)),
            "v": self.pool["v"].at[page, off].set(v_new.astype(dt)),
        }

    def gathered(self):
        B, npages = self._table.shape
        shape = (B, npages * self._pg) + self.pool["k"].shape[2:]
        k = jnp.take(self.pool["k"], self._table, axis=0).reshape(shape)
        v = jnp.take(self.pool["v"], self._table, axis=0).reshape(shape)
        return k, v


class _ContiguousView:
    """Contiguous-strip cache window: ``pool`` {"k","v"}: [S+1, L+1, H, D]
    (row S and column L are padding scratch); ``slots`` [B] int32."""

    def __init__(self, pool, slots, positions, valid):
        self.pool = pool
        self._slots = slots
        self._pos = positions
        self._valid = valid

    def append(self, k_new, v_new):
        n_slots, length = self.pool["k"].shape[:2]
        row = jnp.broadcast_to(self._slots[:, None], self._pos.shape)
        row = jnp.where(self._valid, row, n_slots - 1)
        col = jnp.where(self._valid, jnp.clip(self._pos, 0, length - 1),
                        length - 1)
        dt = self.pool["k"].dtype
        self.pool = {
            "k": self.pool["k"].at[row, col].set(k_new.astype(dt)),
            "v": self.pool["v"].at[row, col].set(v_new.astype(dt)),
        }

    def gathered(self):
        return (jnp.take(self.pool["k"], self._slots, axis=0),
                jnp.take(self.pool["v"], self._slots, axis=0))


# ---------------------------------------------------------------------------
# The program
# ---------------------------------------------------------------------------


class DecodeProgram:
    """Compiled decode/prefill step + device cache pools for ONE model.

    Owns: the layer plan (which layers cache, which are positionwise), the
    page pool / contiguous strips, and the AOT-wrapped jitted step
    (site ``decode.step`` on ``model._aot_fns`` — bundle persistence and
    restore ride the existing nn/aot.py machinery). Host-side page
    accounting (free lists, per-stream page lists) belongs to the caller
    (serve/scheduler.GenerateWorker); the program only consumes table
    slices whose SHAPES are already on the ladder.
    """

    def __init__(self, model, *, page_tokens: int = 64, max_batch: int = 8,
                 prefill_chunk: int = 64, paged: bool = True,
                 capacity: Optional[int] = None,
                 ladder: Optional[bucketing.BucketLadder] = None):
        from deeplearning4j_tpu.nn.layers import (
            ActivationLayer, DropoutLayer, EmbeddingSequence, LayerNorm,
            PositionalEmbedding, TransformerBlock)

        if model.params is None:
            model.init()
        self.model = model
        self.ladder = ladder or bucketing.ladder_from_env()
        self.page_tokens = int(page_tokens)
        self.max_batch = int(max_batch)
        self.prefill_chunk = int(prefill_chunk)
        self.paged = bool(paged)
        if self.page_tokens < 1 or self.max_batch < 1 or self.prefill_chunk < 1:
            raise ValueError("page_tokens, max_batch and prefill_chunk must "
                             "be >= 1")

        # layer plan: every layer must be cache-aware or provably
        # positionwise (token t's output depends only on token t) — anything
        # else would silently corrupt incremental decode
        positionwise = (EmbeddingSequence, LayerNorm, DropoutLayer,
                        ActivationLayer)
        plan: List[Tuple[str, object]] = []
        pos_cap = None
        for i, layer in enumerate(model.layers):
            last = i == len(model.layers) - 1
            if isinstance(layer, TransformerBlock):
                plan.append(("block", layer))
            elif isinstance(layer, PositionalEmbedding):
                plan.append(("pos", layer))
                cap = int(layer.max_len)
                pos_cap = cap if pos_cap is None else min(pos_cap, cap)
            elif last and hasattr(layer, "preactivation"):
                plan.append(("out", layer))
            elif isinstance(layer, positionwise):
                plan.append(("through", layer))
            else:
                raise ValueError(
                    f"DecodeProgram: layer {i} ({type(layer).__name__}) has "
                    f"no decode path and is not positionwise — incremental "
                    f"decode would be wrong")
        if plan[-1][0] != "out":
            raise ValueError("DecodeProgram: the final layer must expose "
                             "preactivation() (logits head)")
        self._plan = plan
        self._blocks = [l for kind, l in plan if kind == "block"]
        if not self._blocks:
            raise ValueError("DecodeProgram: model has no TransformerBlock "
                             "to cache")

        self.capacity = int(capacity if capacity is not None
                            else (pos_cap or 512))
        self.max_pages = max(1, math.ceil(self.capacity / self.page_tokens))
        # contiguous strips align to the page grid so both layouts mask the
        # same maximal span
        self.contig_len = self.max_pages * self.page_tokens

        # per-block head geometry from the resolved input types
        self._geom = []
        for i, layer in enumerate(model.layers):
            if isinstance(layer, TransformerBlock):
                C = model.layer_input_types[i].size
                self._geom.append((int(layer.n_heads),
                                   C // int(layer.n_heads)))
        self.pools = self._alloc_pools()
        # the serve executor's step program: donates only the cache pools
        # (params/state are shared across concurrent streams)
        from deeplearning4j_tpu.nn.step_program import StepProgram

        self._fn = StepProgram(self._step, SITE, model=model,
                               donate_argnums=(2,))

    # -- cache allocation ---------------------------------------------------

    def _alloc_pools(self):
        dt = self.model.dtype
        pools = []
        for H, D in self._geom:
            if self.paged:
                P = 1 + self.max_batch * self.max_pages  # +1: scratch page 0
                shape = (P, self.page_tokens, H, D)
            else:
                shape = (self.max_batch + 1, self.contig_len + 1, H, D)
            pools.append({"k": jnp.zeros(shape, dt),
                          "v": jnp.zeros(shape, dt)})
        return tuple(pools)

    def reset(self):
        """Zero the cache pools (stream isolation is by page/slot ownership,
        so this is for tests, not per-request hygiene)."""
        self.pools = self._alloc_pools()

    # -- the jitted step -----------------------------------------------------

    def _step(self, params, state, pools, table, lengths, tokens, n_new):
        """One decode/prefill step. ``table``: [B, NP] page table slice
        (paged) or [B] slot ids (contiguous); ``lengths`` [B]: tokens
        already cached per row; ``tokens`` [B, Tc] int32 chunk (padding 0);
        ``n_new`` [B]: real tokens in each row's chunk. Returns
        ``(pools', logits [B, V] f32 at each row's last real token,
        greedy ids [B] int32)``."""
        B, Tc = tokens.shape
        span = (table.shape[1] * self.page_tokens if self.paged
                else self.contig_len + 1)
        # python body runs once per trace -> counts actual compiles
        bucketing.telemetry().record_trace(SITE, (B, Tc, span))
        positions = lengths[:, None] + jnp.arange(Tc, dtype=jnp.int32)[None]
        valid = jnp.arange(Tc, dtype=jnp.int32)[None] < n_new[:, None]
        a = tokens
        new_pools = list(pools)
        bi = 0
        logits = None
        for li, (kind, layer) in enumerate(self._plan):
            p = params[li]
            if kind == "block":
                if self.paged:
                    view = _PagedView(new_pools[bi], table, positions, valid,
                                      self.page_tokens)
                else:
                    view = _ContiguousView(new_pools[bi], table, positions,
                                           valid)
                a = layer.decode_apply(p, a, cache=view, positions=positions)
                new_pools[bi] = view.pool
                bi += 1
            elif kind == "pos":
                a = layer.decode_apply(p, a, positions)
            elif kind == "out":
                last = jnp.clip(n_new - 1, 0, Tc - 1).astype(jnp.int32)
                a_last = jnp.take_along_axis(a, last[:, None, None],
                                             axis=1)[:, 0]        # [B, C]
                logits = layer.preactivation(p, a_last).astype(jnp.float32)
            else:  # positionwise passthrough, eval mode
                a, _ = layer.apply(p, state[li], a, train=False, rng=None,
                                   mask=None)
        ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return tuple(new_pools), logits, ids

    # -- dispatch ------------------------------------------------------------

    def dispatch(self, table, lengths, tokens, n_new):
        """Run one step over the live pools (donated in, replaced out).
        Array args are host arrays shaped to ladder buckets by the caller;
        returns ``(logits, ids)`` still on device."""
        table = jnp.asarray(np.asarray(table, np.int32))
        lengths = jnp.asarray(np.asarray(lengths, np.int32))
        tokens = jnp.asarray(np.asarray(tokens, np.int32))
        n_new = jnp.asarray(np.asarray(n_new, np.int32))
        self.pools, logits, ids = self._fn(
            self.model.params, self.model.state, self.pools, table, lengths,
            tokens, n_new)
        return logits, ids

    # -- AOT warm ------------------------------------------------------------

    def signature_grid(self):
        """The exact (B, Tc, NP) dispatch grid the serving tier can reach:
        decode at Tc=1 over every (batch bucket x table bucket), prefill at
        B=1 over every (chunk bucket x table bucket). NP is None in
        contiguous mode (table width is not a dispatch axis)."""
        b_buckets = aot.reachable_buckets(self.max_batch, self.ladder)
        t_buckets = aot.reachable_buckets(self.prefill_chunk, self.ladder)
        p_buckets = (aot.reachable_buckets(self.max_pages, self.ladder)
                     if self.paged else [None])
        grid = []
        for np_b in p_buckets:
            for b in b_buckets:
                grid.append((b, 1, np_b))
            for tc in t_buckets:
                if tc != 1:
                    grid.append((1, tc, np_b))
        return grid

    def warm(self) -> int:
        """AOT-compile the full reachable decode/prefill executable set so
        the token path never compiles (the serve_smoke.sh zero-compile
        gate). Idempotent; returns the number of executables now warm."""
        t0 = time.perf_counter()
        for b, tc, np_b in self.signature_grid():
            if self.paged:
                table = jnp.zeros((b, np_b), jnp.int32)
            else:
                table = jnp.zeros((b,), jnp.int32)
            self._fn.warm(
                self.model.params, self.model.state, self.pools, table,
                jnp.zeros((b,), jnp.int32), jnp.zeros((b, tc), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                cost_key=f"b{b}t{tc}" + (f"p{np_b}" if np_b else ""))
        obs.event("aot_warmup", site=SITE,
                  executables=self._fn.compiled_count,
                  duration_s=round(time.perf_counter() - t0, 6))
        return self._fn.compiled_count

    @property
    def compiled_count(self) -> int:
        return self._fn.compiled_count
