"""Input preprocessors: shape adapters auto-inserted between layer kinds.

Reference parity: nn/conf/preprocessor/{CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor,FeedForwardToRnnPreProcessor,
RnnToFeedForwardPreProcessor,CnnToRnnPreProcessor,RnnToCnnPreProcessor}.java.

Implemented as param-free layers so they flow through the same registry /
serde / apply machinery. Because this framework's Dense natively handles
[batch, time, feat], the FF<->RNN reshape pair is only needed when the user
explicitly wants flattened time-steps.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.config import LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


@register_layer("pp_cnn_to_ff")
@dataclass
class CnnToFeedForward(LayerConfig):
    """[b,h,w,c] -> [b, h*w*c]."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.height * input_type.width * input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape(x.shape[0], -1), state


@register_layer("pp_ff_to_cnn")
@dataclass
class FeedForwardToCnn(LayerConfig):
    """[b, h*w*c] -> [b,h,w,c] (also serves conv_flat -> conv)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels), state


@register_layer("pp_rnn_to_ff")
@dataclass
class RnnToFeedForward(LayerConfig):
    """[b,t,f] -> [b*t, f] (time-step flattening as in
    RnnToFeedForwardPreProcessor; batch axis grows by t)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape(-1, x.shape[-1]), state

    def propagate_mask(self, mask, input_type):
        return mask.reshape(-1) if mask is not None else None


@register_layer("pp_ff_to_rnn")
@dataclass
class FeedForwardToRnn(LayerConfig):
    """[b*t, f] -> [b,t,f]; needs static timesteps."""

    timesteps: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.size, self.timesteps)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape(-1, self.timesteps, x.shape[-1]), state

    def propagate_mask(self, mask, input_type):
        return mask.reshape(-1, self.timesteps) if mask is not None else None


@register_layer("pp_cnn_to_rnn")
@dataclass
class CnnToRnn(LayerConfig):
    """[b,h,w,c] -> [b, h, w*c] treating height as time
    (CnnToRnnPreProcessor flattens channels*width per row)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.width * input_type.channels, input_type.height)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b, h, w, c = x.shape
        return x.reshape(b, h, w * c), state


@register_layer("pp_rnn_to_cnn")
@dataclass
class RnnToCnn(LayerConfig):
    """[b,t,f] -> [b,h,w,c] per timestep folded into height."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels), state


@register_layer("reshape")
@dataclass
class Reshape(LayerConfig):
    """Generic reshape (ReshapeVertex equivalent); shape excludes batch."""

    shape: tuple = ()

    def output_type(self, input_type: InputType) -> InputType:
        s = tuple(self.shape)
        if len(s) == 1:
            return InputType.feed_forward(s[0])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        raise ValueError(f"Unsupported reshape target {s}")

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape((x.shape[0],) + tuple(self.shape)), state


def infer_preprocessor(from_type: InputType, to_layer) -> Optional[LayerConfig]:
    """Auto-insert a shape adapter, mirroring the reference's
    ``setInputType``/preprocessor inference. Returns None if shapes already
    line up.

    Three layer groups matter:
    - conv layers (need [b,h,w,c] input),
    - rnn layers (need [b,t,f] input),
    - shape-preserving layers (BatchNorm, dropout/noise, activation, global
      pooling): consume ANY rank natively — never insert an adapter for them.
    Everything else (Dense, Output, Embedding, ...) consumes flat [b, f].
    """
    from deeplearning4j_tpu.nn.layers.convolution import (
        Conv1D,
        Conv2D,
        Subsampling1D,
        Subsampling2D,
        Upsampling2D,
        ZeroPadding2D,
    )
    from deeplearning4j_tpu.nn.layers.core import (
        ActivationLayer,
        AlphaDropout,
        DropoutLayer,
        ELULayer,
        GaussianDropout,
        GaussianNoise,
        LeakyReLULayer,
        PReLU,
        ThresholdedReLULayer,
    )
    from deeplearning4j_tpu.nn.layers.normalization import BatchNorm, LocalResponseNormalization
    from deeplearning4j_tpu.nn.layers.pooling import GlobalPooling
    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrent, Bidirectional, LastTimeStep, MaskZero

    conv_layers = (Conv2D, Subsampling2D, Upsampling2D, ZeroPadding2D, LocalResponseNormalization)
    rnn_layers = (BaseRecurrent, Bidirectional, LastTimeStep, MaskZero, Conv1D, Subsampling1D)
    shape_preserving = (
        BatchNorm,
        GlobalPooling,
        ActivationLayer,
        DropoutLayer,
        GaussianNoise,
        GaussianDropout,
        AlphaDropout,
        # parameterized activations consume any rank natively (PReLU's
        # learned alpha follows the input shape at init time)
        LeakyReLULayer,
        ELULayer,
        ThresholdedReLULayer,
        PReLU,
    )

    if isinstance(to_layer, shape_preserving):
        return None
    if getattr(to_layer, "CONSUMES_CONV", False) and from_type.kind in ("conv", "conv_flat"):
        # layers that natively take [b,h,w,c] without being "conv layers"
        # (Cropping2D, Yolo2OutputLayer, CnnLossLayer)
        if from_type.kind == "conv_flat":
            return FeedForwardToCnn(height=from_type.height, width=from_type.width,
                                    channels=from_type.channels)
        return None
    if isinstance(to_layer, conv_layers) and from_type.kind == "conv_flat":
        return FeedForwardToCnn(height=from_type.height, width=from_type.width, channels=from_type.channels)
    if isinstance(to_layer, conv_layers) and from_type.kind == "ff":
        raise ValueError(
            "Feed-forward input into a convolutional layer: specify "
            "InputType.convolutional_flat(...) so the reshape target is known"
        )
    if from_type.kind == "conv" and not isinstance(to_layer, conv_layers):
        if isinstance(to_layer, rnn_layers):
            return CnnToRnn()
        return CnnToFeedForward()
    if from_type.kind == "conv_flat" and not isinstance(to_layer, conv_layers):
        # Dense etc. consume the flat vector directly.
        return None
    return None
