"""Weight initialization schemes.

Parity with the reference's ``WeightInit`` enum and ``WeightInitUtil``
(/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/weights/WeightInit.java:68)
— XAVIER, RELU, LECUN, uniform/normal variants, DISTRIBUTION, IDENTITY —
expressed as pure ``init(key, shape, fan_in, fan_out) -> Array`` functions so
they can run inside a jitted init and respect the param sharding they are
created under.

DL4J semantics notes (WeightInitUtil.java):
  - XAVIER       = N(0, 2/(fan_in+fan_out))
  - XAVIER_UNIFORM = U(±sqrt(6/(fan_in+fan_out)))
  - XAVIER_FAN_IN  = N(0, 1/fan_in)
  - RELU         = N(0, 2/fan_in)
  - RELU_UNIFORM = U(±sqrt(6/fan_in))
  - SIGMOID_UNIFORM = U(±4*sqrt(6/(fan_in+fan_out)))
  - LECUN_NORMAL = N(0, 1/fan_in); LECUN_UNIFORM = U(±sqrt(3/fan_in))
  - UNIFORM      = U(±1/sqrt(fan_in))  (legacy default)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

InitFn = Callable[[jax.Array, Sequence[int], float, float, jnp.dtype], jax.Array]

_REGISTRY: Dict[str, InitFn] = {}


def register(name: str):
    def deco(fn: InitFn) -> InitFn:
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


def get(name_or_fn) -> InitFn:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown weight init '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names() -> list:
    return sorted(_REGISTRY)


@register("zero")
def zero(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


@register("ones")
def ones(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


@register("normal")
def normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J NORMAL: N(0, 1/sqrt(fan_in))
    std = 1.0 / math.sqrt(max(fan_in, 1.0))
    return std * jax.random.normal(key, shape, dtype)


@register("uniform")
def uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = 1.0 / math.sqrt(max(fan_in, 1.0))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("xavier")
def xavier(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(2.0 / max(fan_in + fan_out, 1.0))
    return std * jax.random.normal(key, shape, dtype)


@register("xavier_uniform")
def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / max(fan_in + fan_out, 1.0))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("xavier_fan_in")
def xavier_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / max(fan_in, 1.0))
    return std * jax.random.normal(key, shape, dtype)


@register("relu")
def relu(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(2.0 / max(fan_in, 1.0))
    return std * jax.random.normal(key, shape, dtype)


@register("relu_uniform")
def relu_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / max(fan_in, 1.0))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("sigmoid_uniform")
def sigmoid_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = 4.0 * math.sqrt(6.0 / max(fan_in + fan_out, 1.0))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("lecun_normal")
def lecun_normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / max(fan_in, 1.0))
    return std * jax.random.normal(key, shape, dtype)


@register("lecun_uniform")
def lecun_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(3.0 / max(fan_in, 1.0))
    return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)


@register("identity")
def identity_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    if len(shape) != 2 or shape[0] != shape[1]:
        raise ValueError(f"IDENTITY init requires a square 2-D shape, got {shape}")
    return jnp.eye(shape[0], dtype=dtype)


@register("varscaling_normal_fan_in")
def vs_normal_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return math.sqrt(1.0 / max(fan_in, 1.0)) * jax.random.normal(key, shape, dtype)


@register("varscaling_normal_fan_out")
def vs_normal_fan_out(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return math.sqrt(1.0 / max(fan_out, 1.0)) * jax.random.normal(key, shape, dtype)


@register("varscaling_normal_fan_avg")
def vs_normal_fan_avg(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return math.sqrt(2.0 / max(fan_in + fan_out, 1.0)) * jax.random.normal(key, shape, dtype)


@dataclass(frozen=True)
class Distribution:
    """DL4J WeightInit.DISTRIBUTION equivalent: explicit sampling distribution.

    kind: "normal" | "uniform" | "truncated_normal" | "constant"
    """

    kind: str = "normal"
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    value: float = 0.0

    def __call__(self, key, shape, fan_in, fan_out, dtype=jnp.float32):
        if self.kind == "normal":
            return self.mean + self.std * jax.random.normal(key, shape, dtype)
        if self.kind == "uniform":
            return jax.random.uniform(key, shape, dtype, minval=self.lower, maxval=self.upper)
        if self.kind == "truncated_normal":
            return self.mean + self.std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
        if self.kind == "constant":
            return jnp.full(shape, self.value, dtype)
        raise ValueError(f"Unknown distribution kind '{self.kind}'")

    def to_dict(self):
        return {
            "kind": self.kind,
            "mean": self.mean,
            "std": self.std,
            "lower": self.lower,
            "upper": self.upper,
            "value": self.value,
        }

    @staticmethod
    def from_dict(d):
        return Distribution(**d)


def initialize(
    name_or_fn,
    key: jax.Array,
    shape: Sequence[int],
    fan_in: float,
    fan_out: float,
    dtype=jnp.float32,
) -> jax.Array:
    """Initialize one tensor. `name_or_fn` may be a registry name, a
    Distribution, or any callable with the InitFn signature."""
    fn = get(name_or_fn)
    return fn(key, tuple(shape), float(fan_in), float(fan_out), dtype)
