"""Loss functions.

Capability parity with ND4J's ``ILossFunction`` family used by the reference's
output layers (MCXENT, NEGATIVELOGLIKELIHOOD, MSE, MAE, L1, L2, XENT, HINGE,
SQUARED_HINGE, KL_DIVERGENCE, POISSON, COSINE_PROXIMITY, MSLE, MAPE, WASSERSTEIN).

Design: each loss is a pure function
    ``loss(labels, output, mask=None, weights=None) -> per-example scores [batch]``
where `output` is the POST-activation network output (DL4J convention). A
separate :func:`compute` entry point takes pre-activation values and fuses the
numerically-unstable pairs (softmax+MCXENT -> log_softmax cross-entropy,
sigmoid+XENT -> logits BCE) so the jitted training step never materialises
``log(softmax(z))`` — the fused forms are also what XLA pattern-matches best.

Masking follows the reference's per-timestep mask semantics
(score array is multiplied by the mask and averaged over unmasked entries,
cf. MaskedReductionUtil in /root/reference/deeplearning4j-nn/.../util/).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

EPS = 1e-7

LossFn = Callable[..., jax.Array]

_REGISTRY: Dict[str, LossFn] = {}


def register(name: str, *aliases: str):
    def deco(fn: LossFn) -> LossFn:
        _REGISTRY[name.lower()] = fn
        for a in aliases:
            _REGISTRY[a.lower()] = fn
        return fn

    return deco


def get(name_or_fn) -> LossFn:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names() -> list:
    return sorted(_REGISTRY)


def _sum_features(x: jax.Array) -> jax.Array:
    """Sum over all non-batch axes -> per-example score [batch]."""
    return jnp.sum(x, axis=tuple(range(1, x.ndim)))


def _apply_weights(x: jax.Array, weights) -> jax.Array:
    if weights is None:
        return x
    return x * jnp.asarray(weights, x.dtype)


@register("mse")
def mse(labels, output, weights=None):
    d = _apply_weights((output - labels) ** 2, weights)
    # DL4J LossMSE divides by the number of output features (vs L2 which doesn't)
    n = labels.shape[-1] if labels.ndim > 1 else 1
    return _sum_features(d) / n


@register("l2")
def l2(labels, output, weights=None):
    return _sum_features(_apply_weights((output - labels) ** 2, weights))


@register("mae")
def mae(labels, output, weights=None):
    n = labels.shape[-1] if labels.ndim > 1 else 1
    return _sum_features(_apply_weights(jnp.abs(output - labels), weights)) / n


@register("l1")
def l1(labels, output, weights=None):
    return _sum_features(_apply_weights(jnp.abs(output - labels), weights))


@register("mcxent", "negativeloglikelihood")
def mcxent(labels, output, weights=None):
    """Multi-class cross entropy on probabilities: -sum(y * log(p))."""
    logp = jnp.log(jnp.clip(output, EPS, 1.0))
    return _sum_features(_apply_weights(-labels * logp, weights))


@register("xent")
def xent(labels, output, weights=None):
    """Binary cross entropy on probabilities (per-output independent)."""
    p = jnp.clip(output, EPS, 1.0 - EPS)
    ce = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    return _sum_features(_apply_weights(ce, weights))


@register("hinge")
def hinge(labels, output, weights=None):
    # labels in {-1, +1} (DL4J converts 0/1 -> -1/+1); here expect ±1.
    return _sum_features(_apply_weights(jnp.maximum(0.0, 1.0 - labels * output), weights))


@register("squared_hinge", "squaredhinge")
def squared_hinge(labels, output, weights=None):
    h = jnp.maximum(0.0, 1.0 - labels * output)
    return _sum_features(_apply_weights(h * h, weights))


@register("kl_divergence", "kld", "reconstruction_crossentropy")
def kld(labels, output, weights=None):
    y = jnp.clip(labels, EPS, 1.0)
    p = jnp.clip(output, EPS, 1.0)
    return _sum_features(_apply_weights(y * (jnp.log(y) - jnp.log(p)), weights))


@register("poisson")
def poisson(labels, output, weights=None):
    p = jnp.clip(output, EPS, None)
    return _sum_features(_apply_weights(p - labels * jnp.log(p), weights))


@register("cosine_proximity")
def cosine_proximity(labels, output, weights=None):
    yn = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), EPS)
    pn = output / jnp.maximum(jnp.linalg.norm(output, axis=-1, keepdims=True), EPS)
    return _sum_features(_apply_weights(-yn * pn, weights))


@register("msle")
def msle(labels, output, weights=None):
    d = jnp.log1p(jnp.clip(output, -1 + EPS, None)) - jnp.log1p(jnp.clip(labels, -1 + EPS, None))
    n = labels.shape[-1] if labels.ndim > 1 else 1
    return _sum_features(_apply_weights(d * d, weights)) / n


@register("mape")
def mape(labels, output, weights=None):
    d = jnp.abs((labels - output) / jnp.clip(jnp.abs(labels), EPS, None)) * 100.0
    n = labels.shape[-1] if labels.ndim > 1 else 1
    return _sum_features(_apply_weights(d, weights)) / n


@register("wasserstein")
def wasserstein(labels, output, weights=None):
    return _sum_features(_apply_weights(labels * output, weights))


# ---------------------------------------------------------------------------
# Fused, numerically-stable entry point used by output layers.
# ---------------------------------------------------------------------------


def per_example_scores(
    loss,
    labels: jax.Array,
    preact: jax.Array,
    activation: str = "identity",
    mask: Optional[jax.Array] = None,
    weights=None,
) -> jax.Array:
    """Per-example loss scores from PRE-activation output.

    Fuses (softmax, mcxent) and (sigmoid, xent) into stable logit-space forms;
    otherwise applies the activation then the probability-space loss.

    For rank-3 time-series inputs [batch, time, feat], a 2-D mask
    [batch, time] zeroes masked timesteps BEFORE summation, matching the
    reference's masked scoring.
    """
    from deeplearning4j_tpu.nn import activations as _act

    loss_name = loss if isinstance(loss, str) else None
    if loss_name is not None:
        loss_name = loss_name.lower()

    labels = jnp.asarray(labels)
    # SPARSE integer labels (beyond-reference convenience): class INDICES of
    # rank preact.ndim-1 instead of one-hot — at vocab-scale heads this
    # removes the [B,(T,)C] one-hot tensor entirely (268MB at B16 T2048
    # V2048 f32). Supported for the fused softmax+MCXENT path only.
    sparse = (labels.ndim == preact.ndim - 1
              and jnp.issubdtype(labels.dtype, jnp.integer))
    if sparse and not (loss_name in ("mcxent", "negativeloglikelihood")
                       and str(activation).lower() == "softmax"):
        raise ValueError(
            "integer (sparse) labels are only supported for the "
            "softmax+mcxent loss head; one-hot labels required for "
            f"loss={loss_name!r} activation={activation!r}")

    if loss_name in ("mcxent", "negativeloglikelihood") and str(activation).lower() == "softmax":
        logp = jax.nn.log_softmax(preact, axis=-1)
        if sparse:
            lab = labels.astype(jnp.int32)
            ce = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
            if weights is not None:
                ce = ce * jnp.asarray(weights, ce.dtype)[lab]
            if preact.ndim == 3 and mask is not None and mask.ndim == 2:
                return jnp.sum(ce * mask, axis=-1)
            if preact.ndim == 3:
                # dense convention: per-example score sums over time
                ce = jnp.sum(ce, axis=-1)
            if mask is not None:
                # [B] example mask (incl. padding validity weights) applies
                # after the time sum, same as the dense rank-3 path
                ce = ce * mask.reshape(ce.shape)
            return ce  # [B]
        elem = -labels * logp
        if weights is not None:
            elem = elem * jnp.asarray(weights, elem.dtype)
    elif loss_name == "xent" and str(activation).lower() == "sigmoid":
        # stable BCE with logits: logaddexp(0, z) - z*y == log(1+e^z) - z*y.
        # NOT the max(z,0)+log1p(exp(-|z|)) spelling: that form is smooth in
        # value but kinked in expression, so AD at z == 0 exactly (a fully
        # relu-dead row under a zero-init bias) returns -y instead of the
        # true sigmoid(0)-y. logaddexp computes the same stable value with
        # the correct derivative everywhere.
        z = preact
        elem = jnp.logaddexp(0.0, z) - z * labels
        if weights is not None:
            elem = elem * jnp.asarray(weights, elem.dtype)
    else:
        out = _act.get(activation)(preact)
        fn = get(loss)
        if mask is not None and preact.ndim == 3 and mask.ndim == 2:
            # Per-timestep scores, masked before summing over time.
            elem_scores = fn(
                labels.reshape(-1, labels.shape[-1]),
                out.reshape(-1, out.shape[-1]),
                weights=weights,
            ).reshape(mask.shape)
            return jnp.sum(elem_scores * mask, axis=-1)
        per_ex = fn(labels, out, weights=weights)
        if mask is not None:
            per_ex = per_ex * mask.reshape(per_ex.shape)
        return per_ex

    if elem.ndim == 3 and mask is not None and mask.ndim == 2:
        return jnp.sum(jnp.sum(elem, axis=-1) * mask, axis=-1)
    per_ex = _sum_features(elem)
    if mask is not None:
        per_ex = per_ex * mask.reshape(per_ex.shape)
    return per_ex


def average_score(
    loss,
    labels: jax.Array,
    preact: jax.Array,
    activation: str = "identity",
    mask: Optional[jax.Array] = None,
    weights=None,
) -> jax.Array:
    """Mean loss over examples (over unmasked timesteps for rank-3 + mask),
    matching the reference's score averaging in BaseOutputLayer.computeScore."""
    scores = per_example_scores(loss, labels, preact, activation, mask, weights)
    if mask is not None and preact.ndim == 3 and mask.ndim == 2:
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(scores) / denom
    if mask is not None:
        # Per-example mask: reference parity — BaseOutputLayer.computeScore
        # divides by the FULL minibatch size even when a label mask is
        # present (score /= getInputMiniBatchSize()), so a user-supplied
        # example mask zeroes contributions without shrinking the
        # denominator. ParallelWrapper's internal padding masks recover
        # exact sum/n semantics by pre-scaling the mask by B_pad/n (see
        # parallel/wrapper.py _padded_lmask) — this branch and the
        # sum/sum(mask) branch above are both compatible with that scaling
        # (the latter is scale-invariant).
        return jnp.sum(scores) / scores.shape[0]
    return jnp.mean(scores)
