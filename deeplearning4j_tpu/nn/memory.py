"""Memory estimation (MemoryReport parity) from XLA's own analysis.

Parity: nn/conf/memory/{MemoryReport.java:70, LayerMemoryReport,
NetworkMemoryReport} — the reference ESTIMATES per-layer fixed/variable
memory by hand-maintained formulas. Here the numbers come from the
compiler that actually allocates: the jitted train/inference executables'
``memory_analysis()`` (argument/output/temp/code sizes), which is exact
for the compiled shapes. On TPU this is strictly more valuable than the
reference's arithmetic — HBM is a fixed budget and XLA's temp buffer is
the real footprint.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _tree_bytes(tree) -> int:
    return sum(
        int(np.prod(np.shape(l))) * jnp.asarray(l).dtype.itemsize
        for l in jax.tree_util.tree_leaves(tree)
    )


@dataclass
class MemoryReport:
    """Network memory report (NetworkMemoryReport surface): fixed memory
    (params + updater state), and per-mode compiled-executable footprints."""

    model_class: str
    batch_size: int
    params_bytes: int
    opt_state_bytes: int
    inference: Dict[str, int] = field(default_factory=dict)
    training: Dict[str, int] = field(default_factory=dict)

    def total_training_bytes(self) -> int:
        return (self.params_bytes + self.opt_state_bytes
                + self.training.get("temp_bytes", 0)
                + self.training.get("output_bytes", 0))

    def total_inference_bytes(self) -> int:
        return (self.params_bytes + self.inference.get("temp_bytes", 0)
                + self.inference.get("output_bytes", 0))

    def to_string(self) -> str:
        mb = lambda b: f"{b / 2**20:.2f} MB"
        lines = [
            f"MemoryReport: {self.model_class} (batch={self.batch_size})",
            f"  parameters:     {mb(self.params_bytes)}",
            f"  updater state:  {mb(self.opt_state_bytes)}",
            f"  inference:      temp {mb(self.inference.get('temp_bytes', 0))}, "
            f"output {mb(self.inference.get('output_bytes', 0))}, "
            f"total {mb(self.total_inference_bytes())}",
            f"  training:       temp {mb(self.training.get('temp_bytes', 0))}, "
            f"output {mb(self.training.get('output_bytes', 0))}, "
            f"total {mb(self.total_training_bytes())}",
        ]
        return "\n".join(lines)


def _analyze(compiled) -> Dict[str, int]:
    try:
        ma = compiled.memory_analysis()
        return {
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
            "code_bytes": int(getattr(ma, "generated_code_size_in_bytes", 0)),
        }
    except Exception:  # backend without memory_analysis support
        return {}


def _dummy_for(it, batch_size: int, dtype):
    if it.kind == "conv":
        return jnp.zeros((batch_size, it.height, it.width, it.channels), dtype)
    if it.kind == "recurrent":
        return jnp.zeros((batch_size, it.timesteps or 16, it.size), dtype)
    return jnp.zeros((batch_size, it.flat_size()), dtype)


def _memory_report_cg(model, batch_size: int) -> MemoryReport:
    """ComputationGraph variant (NetworkMemoryReport covers both network
    classes in the reference): dummy per-input/per-output arrays from the
    declared InputTypes, same compiled-executable analysis."""
    if model.params is None:
        model.init()
    feats = tuple(_dummy_for(model.conf.input_types[n], batch_size,
                             model.dtype) for n in model.conf.inputs)
    labels = tuple(_dummy_for(t, batch_size, model.dtype)
                   for t in model.output_types)
    inputs = model._input_dict(feats)

    # the model's OWN jitted entry points via the AOT cache: the executables
    # analyzed here are exactly the ones output()/fit_batch() will dispatch,
    # so a report no longer costs a second compile per path (and vice versa
    # — a report AFTER traffic reuses the live executables). ex_weight=None
    # is passed explicitly: jit binds no defaults, so omitting it would key
    # a different signature than fit_batch's call.
    rng = jax.random.PRNGKey(0)
    inf = _analyze(model._get_output_fn().warm(
        model.params, model.state, inputs, None))
    tr = _analyze(model._get_step_fn(False).warm(
        model.params, model.opt_state, model.state,
        jnp.asarray(0, jnp.int32), rng, inputs, labels, None, None, {},
        ex_weight=None,
    ))
    return MemoryReport(
        model_class=type(model).__name__,
        batch_size=batch_size,
        params_bytes=_tree_bytes(model.params),
        opt_state_bytes=_tree_bytes(model.opt_state),
        inference=inf,
        training=tr,
    )


def memory_report(model, batch_size: int = 32) -> MemoryReport:
    """Compile (without executing) the model's inference and train step for
    ``batch_size`` and report exact compiled memory requirements. Covers
    MultiLayerNetwork and ComputationGraph (NetworkMemoryReport parity)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    if isinstance(model, ComputationGraph):
        return _memory_report_cg(model, batch_size)
    if model.params is None:
        model.init()
    x = _dummy_for(model.conf.input_type, batch_size, model.dtype)
    y = _dummy_for(model.output_type, batch_size, model.dtype)

    # the model's OWN jitted entry points via the AOT cache (see the
    # ComputationGraph variant above for why): the inference and training
    # executables analyzed here serve subsequent output()/fit() traffic of
    # the same shape instead of being compiled twice
    rng = jax.random.PRNGKey(0)
    inf = _analyze(model._get_output_fn().warm(
        model.params, model.state, x, None))
    tr = _analyze(model._get_step_fn(False).warm(
        model.params, model.opt_state, model.state,
        jnp.asarray(0, jnp.int32), rng, x, y, None, None, (),
        ex_weight=None,
    ))
    return MemoryReport(
        model_class=type(model).__name__,
        batch_size=batch_size,
        params_bytes=_tree_bytes(model.params),
        opt_state_bytes=_tree_bytes(model.opt_state),
        inference=inf,
        training=tr,
    )
