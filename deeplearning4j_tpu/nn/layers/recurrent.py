"""Recurrent layers: LSTM, GravesLSTM (peepholes), SimpleRnn, Bidirectional,
LastTimeStep, MaskZero, RnnOutputLayer.

Reference parity: the shared fwd/bwd in
/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/layers/recurrent/LSTMHelpers.java:69,393
(used by LSTM / GravesLSTM / GravesBidirectionalLSTM) and the cuDNN fused
path (CudnnLSTMHelper.java). TPU-native design: the time loop is a single
``lax.scan`` whose body is one fused [x,h] @ W matmul on the MXU; backward
comes from autodiff of the scan (XLA keeps the whole unrolled graph on
device — no per-timestep kernel dispatch).

Layout: [batch, time, features] (the reference uses [batch, features, time]).
Masking: mask [batch, time] — masked steps pass the carry through unchanged
and output zeros, matching the reference's masked RNN semantics.

Streaming/tBPTT: every recurrent layer exposes
``initial_carry(batch)`` and ``apply_seq(params, x, carry, mask) ->
(out, new_carry)`` so truncated BPTT is scan-over-chunks with carried state
(SURVEY.md §5.7) and ``rnnTimeStep`` is a one-step call with a stored carry.
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import initializers, losses
from deeplearning4j_tpu.nn.config import FeedForwardLayerConfig, LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


def _mask_step(mask_t, new, old):
    """Where mask_t==0, keep `old`; else `new`. mask_t: [batch]."""
    m = mask_t[:, None]
    return jnp.where(m > 0, new, old)


_FUSED_SUPPRESS_DEPTH = 0


def _fused_suppressed() -> bool:
    return _FUSED_SUPPRESS_DEPTH > 0


@contextmanager
def no_fused_lstm():
    """Trace-time guard: contexts whose SPMD machinery cannot host a
    pallas_call (GPipe's vma-checked rank switch) wrap their step tracing
    in this to force the lax.scan path regardless of policy."""
    global _FUSED_SUPPRESS_DEPTH
    _FUSED_SUPPRESS_DEPTH += 1
    try:
        yield
    finally:
        _FUSED_SUPPRESS_DEPTH -= 1


@dataclass
class BaseRecurrent(FeedForwardLayerConfig):
    """Common recurrent scaffolding."""

    # True for layers with a time-stepped carry (LSTM/SimpleRnn...): enables
    # tBPTT chunking and rnnTimeStep streaming through the model.
    SUPPORTS_CARRY = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def initial_carry(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def _cell(self, params, x_t, carry):
        """One timestep: (params, x_t [b,f], carry) -> new_carry. Default:
        project the single row and delegate to ``_cell_from_proj`` (cells
        that define ``_input_proj`` get this for free; others override)."""
        proj = self._input_proj(params, x_t)
        if proj is None:
            raise NotImplementedError(
                f"{type(self).__name__} must implement _cell or _input_proj")
        return self._cell_from_proj(params, proj, carry)

    def _input_proj(self, params, x):
        """Optional TPU fast path: project the WHOLE [b,t,f] input in one
        [b*t,f]x[f,Z] MXU matmul up front; the scan then consumes the
        precomputed rows via ``_cell_from_proj`` and only runs the recurrent
        [b,h]x[h,Z] matmul per step. Return None to scan raw inputs."""
        return None

    def _cell_from_proj(self, params, zx_t, carry):
        """One timestep from a precomputed input projection row."""
        raise NotImplementedError

    def _carry_output(self, carry):
        """Extract the per-step output h from the carry."""
        return carry

    def apply_seq(self, params, x, carry, mask=None):
        """Shared scan scaffolding: [b,t,f] -> ([b,t,h], final_carry).

        Masked steps pass the carry through unchanged and emit zeros — the
        single implementation of the reference's masked-RNN semantics, used
        by every recurrent cell via the ``_cell``/``_cell_from_proj`` hooks."""
        zx = self._input_proj(params, x)
        if zx is not None:
            stream = zx
            cell = lambda c, v: self._cell_from_proj(params, v, c)
        else:
            stream = x
            cell = lambda c, v: self._cell(params, v, c)

        # tie the carry's device-varying axes to x's: inside shard_map
        # (GPipe stages, ring shards) a constant-zeros carry is unvarying
        # while the scan body's outputs vary over the mesh axes — lax.scan
        # rejects the carry type change. The zero-valued add is free after
        # XLA folding but carries the vma annotation.
        vtie = jnp.sum(x[..., :1]) * 0
        carry = jax.tree_util.tree_map(
            lambda c: c + vtie.astype(c.dtype), carry)

        def step(c, inp):
            v_t, m_t = inp if mask is not None else (inp, None)
            new_c = cell(c, v_t)
            if m_t is not None:
                new_c = jax.tree_util.tree_map(
                    lambda n, o: _mask_step(m_t, n, o), new_c, c
                )
                out = self._carry_output(new_c) * m_t[:, None]
            else:
                out = self._carry_output(new_c)
            return new_c, out

        xs = jnp.swapaxes(stream, 0, 1)  # [time, batch, feat] for scan
        # Scan unroll, overridable via DL4J_TPU_RNN_UNROLL. Round-4 honest
        # re-measure (fresh-process A/B, value-fetch sync): unroll 1/8/50
        # all land within run-to-run noise (~1.8-2.0M tokens/s on the
        # char-RNN bench) — the round-3 "+46% at unroll=8" was a phantom of
        # the sync-elision measurement bug (docs/PERF.md correction).
        # Default kept at 8: never measured worse, bounds compile time.
        import os as _os

        cap = int(_os.environ.get("DL4J_TPU_RNN_UNROLL", "8"))
        unroll = max(1, min(cap, xs.shape[0]))
        if mask is not None:
            ms = jnp.swapaxes(mask.astype(x.dtype), 0, 1)
            final, outs = lax.scan(step, carry, (xs, ms), unroll=unroll)
        else:
            final, outs = lax.scan(step, carry, xs, unroll=unroll)
        return jnp.swapaxes(outs, 0, 1), final

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        carry = self.initial_carry(x.shape[0], x.dtype)
        y, _ = self.apply_seq(params, x, carry, mask)
        return y, state


@register_layer("lstm")
@dataclass
class LSTM(BaseRecurrent):
    """Standard (non-peephole) LSTM — parity with nn/conf/layers/LSTM.java.

    Gate order in the fused kernel: [i, f, g, o] (Keras order, which makes
    Keras h5 import a pure reshape). DL4J's forgetGateBiasInit default of 1.0
    is kept.
    """

    activation: Any = "tanh"
    gate_activation: Any = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.size
        H = self.n_out
        kx, kh = jax.random.split(key)
        Wx = initializers.initialize(self.weight_init, kx, (n_in, 4 * H), n_in, H, dtype)
        Wh = initializers.initialize(self.weight_init, kh, (H, 4 * H), H, H, dtype)
        b = jnp.zeros((4 * H,), dtype)
        # forget-gate block is the second quarter [H:2H]
        b = b.at[H : 2 * H].set(self.forget_gate_bias_init)
        return {"Wx": Wx, "Wh": Wh, "b": b}

    def initial_carry(self, batch: int, dtype=jnp.float32):
        H = self.n_out
        return (jnp.zeros((batch, H), dtype), jnp.zeros((batch, H), dtype))

    def _carry_output(self, carry):
        return carry[0]

    def _input_proj(self, params, x):
        return x @ params["Wx"] + params["b"]

    def _fused_eligible(self) -> bool:
        """The weight-stationary Pallas scan (ops/fused_lstm.py — the
        CudnnLSTMHelper analog) covers the standard and peephole cells
        with default activations and a lane-aligned hidden width."""
        return (self.activation == "tanh"
                and self.gate_activation == "sigmoid"
                and self.n_out % 128 == 0
                and type(self) in (LSTM, GravesLSTM))

    def apply_seq(self, params, x, carry, mask=None):
        import os as _os

        policy = _os.environ.get("DL4J_TPU_FUSED_LSTM", "auto")
        on_tpu = jax.default_backend() == "tpu"
        use_fused = (policy == "1" or (policy == "auto" and on_tpu)) \
            and self._fused_eligible() and not _fused_suppressed()
        if not use_fused:
            return super().apply_seq(params, x, carry, mask)
        from deeplearning4j_tpu.ops.fused_lstm import fused_lstm

        zx = self._input_proj(params, x)
        h0, c0 = carry
        out, (hT, cT) = fused_lstm(zx, params["Wh"], h0, c0, mask,
                                   params.get("peephole"),
                                   interpret=not on_tpu)
        return out, (hT, cT)

    def _cell_from_proj(self, params, zx_t, carry):
        from deeplearning4j_tpu.nn import activations as A

        h, cell = carry
        H = self.n_out
        gate = A.get(self.gate_activation)
        act = A.get(self.activation)
        z = zx_t + h @ params["Wh"]
        i = gate(z[:, 0 * H : 1 * H])
        f = gate(z[:, 1 * H : 2 * H])
        g = act(z[:, 2 * H : 3 * H])
        o = gate(z[:, 3 * H : 4 * H])
        new_cell = f * cell + i * g
        new_h = o * act(new_cell)
        return (new_h, new_cell)



@register_layer("graves_lstm")
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections — parity with GravesLSTM.java
    (LSTMHelpers.java applies peepholes from c_{t-1} to i,f and c_t to o)."""

    def init(self, key, input_type, dtype=jnp.float32):
        params = super().init(key, input_type, dtype)
        H = self.n_out
        params["peephole"] = jnp.zeros((3 * H,), dtype)  # [p_i, p_f, p_o]
        return params

    def _cell_from_proj(self, params, zx_t, carry):
        from deeplearning4j_tpu.nn import activations as A

        h, cell = carry
        H = self.n_out
        act = A.get(self.activation)
        gate = A.get(self.gate_activation)
        p = params["peephole"]
        p_i, p_f, p_o = p[:H], p[H : 2 * H], p[2 * H :]
        z = zx_t + h @ params["Wh"]
        i = gate(z[:, 0 * H : 1 * H] + cell * p_i)
        f = gate(z[:, 1 * H : 2 * H] + cell * p_f)
        g = act(z[:, 2 * H : 3 * H])
        new_cell = f * cell + i * g
        o = gate(z[:, 3 * H : 4 * H] + new_cell * p_o)
        new_h = o * act(new_cell)
        return (new_h, new_cell)



@register_layer("gru")
@dataclass
class GRU(BaseRecurrent):
    """Gated recurrent unit. Gate order [z, r, h] and the ``reset_after``
    switch follow Keras (cuDNN-compatible variant when True, the default) so
    h5 import is a direct weight copy; early DL4J shipped a (since-removed)
    GRU layer — this restores the capability TPU-first with the same
    hoisted-input-projection scan as LSTM."""

    activation: Any = "tanh"
    gate_activation: Any = "sigmoid"
    reset_after: bool = True

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.size
        H = self.n_out
        kx, kh = jax.random.split(key)
        p = {
            "Wx": initializers.initialize(self.weight_init, kx, (n_in, 3 * H),
                                          n_in, H, dtype),
            "Wh": initializers.initialize(self.weight_init, kh, (H, 3 * H),
                                          H, H, dtype),
            "b_in": jnp.zeros((3 * H,), dtype),
        }
        if self.reset_after:
            # separate recurrent bias exists ONLY in the reset_after variant
            # (Keras parity; without it b_rec would be redundant with b_in)
            p["b_rec"] = jnp.zeros((3 * H,), dtype)
        return p

    def initial_carry(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.n_out), dtype)

    def _input_proj(self, params, x):
        return x @ params["Wx"] + params["b_in"]

    def _cell_from_proj(self, params, zx_t, carry):
        from deeplearning4j_tpu.nn import activations as A

        h = carry
        H = self.n_out
        gate = A.get(self.gate_activation)
        act = A.get(self.activation)
        if self.reset_after:
            rec = h @ params["Wh"] + params["b_rec"]
            z = gate(zx_t[:, :H] + rec[:, :H])
            r = gate(zx_t[:, H:2 * H] + rec[:, H:2 * H])
            hh = act(zx_t[:, 2 * H:] + r * rec[:, 2 * H:])
        else:
            rec_zr = h @ params["Wh"][:, :2 * H]
            z = gate(zx_t[:, :H] + rec_zr[:, :H])
            r = gate(zx_t[:, H:2 * H] + rec_zr[:, H:])
            hh = act(zx_t[:, 2 * H:] + (r * h) @ params["Wh"][:, 2 * H:])
        return z * h + (1.0 - z) * hh


@register_layer("simple_rnn")
@dataclass
class SimpleRnn(BaseRecurrent):
    """Elman RNN: h_t = act(x_t Wx + h_{t-1} Wh + b) (SimpleRnn.java)."""

    activation: Any = "tanh"

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.size
        H = self.n_out
        kx, kh = jax.random.split(key)
        return {
            "Wx": initializers.initialize(self.weight_init, kx, (n_in, H), n_in, H, dtype),
            "Wh": initializers.initialize(self.weight_init, kh, (H, H), H, H, dtype),
            "b": jnp.full((H,), self.bias_init, dtype),
        }

    def initial_carry(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.n_out), dtype)

    def _input_proj(self, params, x):
        return x @ params["Wx"] + params["b"]

    def _cell_from_proj(self, params, zx_t, carry):
        return self.activation_fn()(zx_t + carry @ params["Wh"])



@register_layer("bidirectional")
@dataclass
class Bidirectional(LayerConfig):
    """Bidirectional wrapper (conf/layers/recurrent/Bidirectional.java +
    GravesBidirectionalLSTM): runs the wrapped RNN forward and over the
    time-reversed sequence, combining with CONCAT | ADD | MUL | AVERAGE."""

    rnn: Optional[LayerConfig] = None
    mode: str = "concat"

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.rnn.output_type(input_type)
        if self.mode == "concat":
            return InputType.recurrent(inner.size * 2, inner.timesteps)
        return inner

    def init(self, key, input_type, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        return {
            "fwd": self.rnn.init(kf, input_type, dtype),
            "bwd": self.rnn.init(kb, input_type, dtype),
        }

    def nested_param_layers(self) -> dict:
        return {"fwd": self.rnn, "bwd": self.rnn}

    def regularization_penalty(self, params):
        pen = super().regularization_penalty(params)
        return pen + self.rnn.regularization_penalty(params["fwd"]) + \
            self.rnn.regularization_penalty(params["bwd"])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Input dropout: honor both the wrapper's and the wrapped RNN's
        # configured dropout (apply_seq bypasses BaseRecurrent.apply) with
        # independent rng streams.
        if rng is not None:
            rng, rng2 = jax.random.split(rng)
        else:
            rng2 = None
        x = self.maybe_dropout_input(x, train, rng)
        if train and self.rnn.dropout > 0.0:
            x = self.rnn.maybe_dropout_input(x, train, rng2)
        carry_f = self.rnn.initial_carry(x.shape[0], x.dtype)
        carry_b = self.rnn.initial_carry(x.shape[0], x.dtype)
        yf, _ = self.rnn.apply_seq(params["fwd"], x, carry_f, mask)
        xr = jnp.flip(x, axis=1)
        mr = jnp.flip(mask, axis=1) if mask is not None else None
        yb, _ = self.rnn.apply_seq(params["bwd"], xr, carry_b, mr)
        yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        if self.mode == "add":
            return yf + yb, state
        if self.mode == "mul":
            return yf * yb, state
        if self.mode in ("average", "avg"):
            return 0.5 * (yf + yb), state
        raise ValueError(f"Unknown Bidirectional mode '{self.mode}'")


@register_layer("last_time_step")
@dataclass
class LastTimeStep(LayerConfig):
    """Wraps an RNN layer, returning only the last (unmasked) timestep
    (recurrent/LastTimeStepLayer.java): [b,t,f] -> [b,f]."""

    rnn: Optional[LayerConfig] = None

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.rnn.output_type(input_type)
        return InputType.feed_forward(inner.size)

    def init(self, key, input_type, dtype=jnp.float32):
        return self.rnn.init(key, input_type, dtype)

    def regularization_penalty(self, params):
        return super().regularization_penalty(params) + self.rnn.regularization_penalty(params)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y, _ = self.rnn.apply(params, {}, x, train=train, rng=rng, mask=mask)
        if mask is None:
            out = y[:, -1, :]
        else:
            # last index where mask==1 (handles left-padded/ALIGN_END masks,
            # not just contiguous-from-t0)
            T = y.shape[1]
            rev = jnp.flip(mask > 0, axis=1)
            idx = (T - 1 - jnp.argmax(rev, axis=1)).astype(jnp.int32)
            out = jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0, :]
        return out, state

    def propagate_mask(self, mask, input_type):
        return None


@register_layer("bidir_last_time_step")
@dataclass
class BidirectionalLastTimeStep(LayerConfig):
    """Keras ``Bidirectional(rnn, return_sequences=False)`` semantics over a
    wrapped :class:`Bidirectional` (concat mode): the forward half's LAST
    step concatenated with the backward half's step 0 — which is the
    backward RNN's final state, since Bidirectional flips the backward
    output back to input time order. A plain LastTimeStep would wrongly
    take the backward half at t=T-1 (one step of context)."""

    rnn: Optional[LayerConfig] = None  # a Bidirectional, mode="concat"

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.rnn.output_type(input_type)
        return InputType.feed_forward(inner.size)

    def init(self, key, input_type, dtype=jnp.float32):
        if getattr(self.rnn, "mode", "concat") != "concat":
            raise ValueError(
                "BidirectionalLastTimeStep requires mode='concat' (merged "
                "fwd/bwd halves are not separable for other modes)")
        return self.rnn.init(key, input_type, dtype)

    def regularization_penalty(self, params):
        return super().regularization_penalty(params) + self.rnn.regularization_penalty(params)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y, _ = self.rnn.apply(params, {}, x, train=train, rng=rng, mask=mask)
        H = y.shape[-1] // 2
        if mask is None:
            return jnp.concatenate([y[:, -1, :H], y[:, 0, H:]], axis=-1), state
        # masked: fwd half at the LAST valid step, bwd half at the FIRST
        # valid step (= the backward RNN's final state after flip-back;
        # masked steps emit zeros, so the literal endpoints would be wrong
        # for padded sequences)
        T = y.shape[1]
        rev = jnp.flip(mask > 0, axis=1)
        last_idx = (T - 1 - jnp.argmax(rev, axis=1)).astype(jnp.int32)
        first_idx = jnp.argmax(mask > 0, axis=1).astype(jnp.int32)
        fwd = jnp.take_along_axis(
            y[..., :H], last_idx[:, None, None], axis=1)[:, 0, :]
        bwd = jnp.take_along_axis(
            y[..., H:], first_idx[:, None, None], axis=1)[:, 0, :]
        return jnp.concatenate([fwd, bwd], axis=-1), state

    def propagate_mask(self, mask, input_type):
        return None


@register_layer("mask_zero")
@dataclass
class MaskZero(LayerConfig):
    """Derives a mask from timesteps equal to `mask_value` and applies the
    wrapped RNN with it (recurrent/MaskZeroLayer.java)."""

    rnn: Optional[LayerConfig] = None
    mask_value: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        return self.rnn.output_type(input_type)

    def init(self, key, input_type, dtype=jnp.float32):
        return self.rnn.init(key, input_type, dtype)

    def regularization_penalty(self, params):
        return super().regularization_penalty(params) + self.rnn.regularization_penalty(params)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        derived = jnp.any(x != self.mask_value, axis=-1).astype(x.dtype)
        if mask is not None:
            derived = derived * mask
        return self.rnn.apply(params, state, x, train=train, rng=rng, mask=derived)


@register_layer("rnn_output")
@dataclass
class RnnOutputLayer(BaseRecurrent):
    """Time-distributed output layer (RnnOutputLayer.java): dense+loss applied
    at every timestep of [batch, time, feat]."""

    SUPPORTS_CARRY = False  # no recurrence of its own

    activation: Any = "softmax"
    loss: Any = "mcxent"
    has_bias: bool = True

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.size
        kW, _ = jax.random.split(key)
        params = {
            "W": initializers.initialize(self.weight_init, kW, (n_in, self.n_out), n_in, self.n_out, dtype)
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params

    def preactivation(self, params, x):
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = self.activation_fn()(self.preactivation(params, x))
        if mask is not None:
            y = y * mask[..., None]
        return y, state

    def score(self, params, x, labels, mask=None, average=True, weights=None):
        preact = self.preactivation(params, x)
        if average:
            return losses.average_score(self.loss, labels, preact, self.activation, mask, weights)
        return losses.per_example_scores(self.loss, labels, preact, self.activation, mask, weights)
