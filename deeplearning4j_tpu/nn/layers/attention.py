"""Attention / transformer layers — the long-context stack.

Beyond-reference capability (the reference has NO attention layer anywhere —
SURVEY.md §2.5/§5.7; its only long-sequence device is truncated BPTT). Here
transformers are first-class and designed for the TPU:

- ``MultiHeadAttention``: fused qkv projection (one MXU matmul), optional
  causal masking, and optional **sequence parallelism**: when
  ``sequence_parallel=True`` and a mesh with a ``seq`` axis is active (see
  parallel/context.py), attention runs as ring attention over the mesh's
  ``seq`` axis (parallel/ring.py) — K/V blocks rotate over ICI, O(T²) memory
  never materializes on one chip.
- ``TransformerBlock``: pre-LN block (LN→MHA→residual, LN→MLP→residual),
  the standard compilation-friendly composition XLA fuses well.
- ``PositionalEmbedding``: learned positions added to token embeddings.

Tensor parallelism for these layers is sharding metadata, not code: see
parallel/tp.py for the PartitionSpec rules (qkv/mlp-in column-parallel,
out/mlp-out row-parallel).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import initializers
from deeplearning4j_tpu.nn.config import LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


def _mesh_has_axis(axis: str) -> bool:
    from deeplearning4j_tpu.parallel.context import current_mesh

    mesh = current_mesh()
    return mesh is not None and axis in mesh.shape and mesh.shape[axis] > 1


_FLASH_BLOCKS: Dict[str, int] = {}


def _flash_block(var: str, default: int) -> int:
    """Validated value of a DL4J_TPU_FLASH_BLOCK_{Q,K} env knob, parsed ONCE
    per process and cached. A non-integer or non-positive value raises a
    ValueError naming the variable instead of an opaque int() traceback deep
    inside a trace.

    The cached value is baked into the kernel grid at the FIRST trace of the
    flash path — changing the env var later in the process affects neither
    already-compiled executables nor future traces (the cache pins the first
    parse precisely so one process can never mix grids silently)."""
    if var not in _FLASH_BLOCKS:
        import os as _os

        raw = _os.environ.get(var)
        if raw is None:
            _FLASH_BLOCKS[var] = default
        else:
            try:
                val = int(raw)
            except ValueError:
                raise ValueError(
                    f"{var} must be an integer block size (rows per flash "
                    f"kernel tile), got {raw!r}")
            if val <= 0:
                raise ValueError(
                    f"{var} must be a positive block size, got {raw!r}")
            _FLASH_BLOCKS[var] = val
    return _FLASH_BLOCKS[var]


@register_layer("positional_embedding")
@dataclass
class PositionalEmbedding(LayerConfig):
    """Learned positional embedding added to the input sequence [B,T,C]."""

    max_len: int = 512

    def init(self, key, input_type, dtype=jnp.float32):
        return {"pos": jax.random.normal(key, (self.max_len, input_type.size), dtype) * 0.02}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        T = x.shape[1]
        return x + params["pos"][:T][None, :, :], state

    def decode_apply(self, params, x, positions):
        """Decode-mode: positions are per-row absolute indices [B, Tc]
        (a chunk mid-stream starts wherever the row's cache ends), not the
        implicit 0..T-1 of the training path. Clipped, not wrapped: padded
        chunk slots may carry positions past the table; their activations
        are dead (masked by the caller's n_new) either way."""
        idx = jnp.clip(positions, 0, params["pos"].shape[0] - 1)
        return x + jnp.take(params["pos"], idx, axis=0)


@register_layer("multi_head_attention")
@dataclass
class MultiHeadAttention(LayerConfig):
    """Multi-head self-attention over [B, T, C].

    ``sequence_parallel``: run the attention core as ring attention over the
    active mesh's ``seq`` axis (requires T divisible by the axis size and the
    time axis sharded over it).
    """

    n_heads: int = 8
    causal: bool = False
    sequence_parallel: bool = False
    attn_dropout: float = 0.0
    weight_init: Any = "xavier"
    # Pallas flash-attention policy (ops/flash_attention.py): "auto" uses
    # the kernel on TPU — masked (kmask) or not; the [T,T] scores never
    # leave VMEM (at T=8192 the XLA path cannot even compile, PERF.md).
    # True forces it everywhere (Pallas interpreter on CPU — slow, for
    # tests); False always uses the XLA einsum path.
    use_flash: Any = "auto"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def uses_rng(self) -> bool:
        return super().uses_rng() or self.attn_dropout > 0.0

    def init(self, key, input_type, dtype=jnp.float32):
        C = input_type.size
        if C % self.n_heads:
            raise ValueError(f"n_heads={self.n_heads} must divide model dim {C}")
        k1, k2 = jax.random.split(key)
        return {
            # fused qkv: one [C, 3C] matmul onto the MXU
            "Wqkv": initializers.initialize(self.weight_init, k1, (C, 3 * C), C, 3 * C, dtype),
            "bqkv": jnp.zeros((3 * C,), dtype),
            "Wo": initializers.initialize(self.weight_init, k2, (C, C), C, C, dtype),
            "bo": jnp.zeros((C,), dtype),
        }

    def _attend(self, q, k, v, kmask=None):
        from deeplearning4j_tpu.parallel.ring import local_attention, ring_self_attention

        if self.sequence_parallel and _mesh_has_axis("seq"):
            from deeplearning4j_tpu.parallel.context import current_mesh

            mesh = current_mesh()
            # tp+sp composition: when heads are tensor-parallel (column-sharded
            # Wqkv) and divide evenly, keep the head axis sharded through the
            # ring kernel instead of all-gathering activations over "model".
            head_axis = (
                "model"
                if ("model" in mesh.shape and mesh.shape["model"] > 1
                    and q.shape[2] % mesh.shape["model"] == 0)
                else None
            )
            # flash-backed ring (Pallas chunk kernels + exact lse merge) on
            # TPU, same policy as the single-chip flash gate; forced
            # use_flash=True engages it anywhere. kmask rides the ring
            # with its k/v block (round 5 — padded batches keep the flash
            # memory envelope).
            on_tpu = jax.default_backend() == "tpu"
            ring_flash = (
                self.use_flash is True or (self.use_flash == "auto" and on_tpu))
            return ring_self_attention(
                q, k, v, mesh, causal=self.causal, kmask=kmask,
                head_axis=head_axis, use_flash=ring_flash
            )
        if self.use_flash in ("auto", True):
            from deeplearning4j_tpu.ops.flash_attention import flash_attention

            on_tpu = jax.default_backend() == "tpu"
            if self.use_flash is True or on_tpu:
                # off-TPU (interpreter) the compiled XLA-remat backward is
                # far faster than three interpreted Pallas kernels; kmask
                # loads one [1, block_k] validity row per key block in-kernel.
                # Block sizes are env-tunable for perf sweeps; validated and
                # captured at first use (see _flash_block); 128/128 is the
                # measured default.
                bq = _flash_block("DL4J_TPU_FLASH_BLOCK_Q", 128)
                bk = _flash_block("DL4J_TPU_FLASH_BLOCK_K", 128)
                return flash_attention(q, k, v, kmask=kmask,
                                       causal=self.causal,
                                       block_q=bq, block_k=bk,
                                       interpret=not on_tpu,
                                       bwd="pallas" if on_tpu else "xla")
        return local_attention(q, k, v, causal=self.causal, kmask=kmask)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        rng_in, rng_attn = (jax.random.split(rng) if rng is not None else (None, None))
        x = self.maybe_dropout_input(x, train, rng_in)
        B, T, C = x.shape
        H = self.n_heads
        qkv = x @ params["Wqkv"] + params["bqkv"]
        q, k, v = jnp.split(qkv.reshape(B, T, 3 * H, C // H), 3, axis=2)
        kmask = None
        if mask is not None and mask.ndim >= 2:
            kmask = mask.reshape(B, T)  # [B,T] key validity from feature mask
        out = self._attend(q, k, v, kmask)  # [B,T,H,D]
        out = out.reshape(B, T, C)
        if train and self.attn_dropout > 0.0 and rng_attn is not None:
            keep = 1.0 - self.attn_dropout
            out = jnp.where(jax.random.bernoulli(rng_attn, keep, out.shape), out / keep, 0.0)
        return out @ params["Wo"] + params["bo"], state

    def decode_apply(self, params, x, *, cache, positions):
        """Single-query/chunk attention against a KV cache (serving decode
        path, nn/decode.py). ``x`` [B, Tc, C] is the new-token chunk;
        ``cache`` is a cache view (append + gathered, paged or contiguous —
        the layer never sees the paging); ``positions`` [B, Tc] are the
        chunk's absolute positions. Eval-mode by construction: no dropout,
        no rng. The chunk's own k/v are appended to the cache BEFORE the
        gather, so causal self-attention within the chunk and attention
        over the history are one masked span (ops.decode_attention)."""
        from deeplearning4j_tpu.ops.flash_attention import decode_attention

        B, Tc, C = x.shape
        H = self.n_heads
        qkv = x @ params["Wqkv"] + params["bqkv"]
        q, k, v = jnp.split(qkv.reshape(B, Tc, 3 * H, C // H), 3, axis=2)
        cache.append(k, v)
        k_all, v_all = cache.gathered()
        out = decode_attention(q, k_all, v_all, positions)   # [B,Tc,H,D]
        out = out.reshape(B, Tc, C)
        return out @ params["Wo"] + params["bo"]


@register_layer("transformer_block")
@dataclass
class TransformerBlock(LayerConfig):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x)).

    MLP is a fused [C,4C]→gelu→[4C,C] pair (``ffn_mult`` configurable).
    """

    n_heads: int = 8
    ffn_mult: int = 4
    causal: bool = True
    sequence_parallel: bool = False
    activation: Any = "gelu"
    weight_init: Any = "xavier"
    eps: float = 1e-5
    use_flash: Any = "auto"  # forwarded to the nested MultiHeadAttention

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _mha(self) -> MultiHeadAttention:
        return MultiHeadAttention(
            n_heads=self.n_heads,
            causal=self.causal,
            sequence_parallel=self.sequence_parallel,
            weight_init=self.weight_init,
            use_flash=self.use_flash,
        )

    def nested_param_layers(self) -> dict:
        return {"attn": self._mha()}

    def init(self, key, input_type, dtype=jnp.float32):
        C = input_type.size
        F = self.ffn_mult * C
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "attn": self._mha().init(k1, input_type, dtype),
            "ln1": {"gamma": jnp.ones((C,), dtype), "beta": jnp.zeros((C,), dtype)},
            "ln2": {"gamma": jnp.ones((C,), dtype), "beta": jnp.zeros((C,), dtype)},
            "Wi": initializers.initialize(self.weight_init, k2, (C, F), C, F, dtype),
            "bi": jnp.zeros((F,), dtype),
            "Wo": initializers.initialize(self.weight_init, k3, (F, C), F, C, dtype),
            "bo": jnp.zeros((C,), dtype),
        }

    def _ln(self, p, x):
        from deeplearning4j_tpu.nn.layers.normalization import layer_norm

        return layer_norm(x, p["gamma"], p["beta"], self.eps)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        import os as _os

        if _os.environ.get("DL4J_TPU_REMAT_BLOCKS") == "1":
            # per-block rematerialization: trade recompute for activation
            # memory (the classic big-transformer policy; perf-sweepable
            # via tools/exp_transformer_mfu.py remat)
            body = jax.checkpoint(
                lambda p, xx, r, m: self._apply_inner(p, xx, train, r, m))
            return body(params, x, rng, mask), state
        return self._apply_inner(params, x, train, rng, mask), state

    def _apply_inner(self, params, x, train, rng, mask):
        rng_in, rng_attn = (jax.random.split(rng) if rng is not None else (None, None))
        x = self.maybe_dropout_input(x, train, rng_in)
        h = self._ln(params["ln1"], x)
        a, _ = self._mha().apply(params["attn"], {}, h, train=train, rng=rng_attn, mask=mask)
        x = x + a
        h = self._ln(params["ln2"], x)
        h = self.activation_fn()(h @ params["Wi"] + params["bi"])
        return x + (h @ params["Wo"] + params["bo"])

    def decode_apply(self, params, x, *, cache, positions):
        """The block's eval-mode forward for a new-token chunk against a KV
        cache: identical composition to :meth:`_apply_inner` with the MHA
        swapped for its cache-backed decode path (see
        MultiHeadAttention.decode_apply)."""
        h = self._ln(params["ln1"], x)
        a = self._mha().decode_apply(params["attn"], h, cache=cache,
                                     positions=positions)
        x = x + a
        h = self._ln(params["ln2"], x)
        h = self.activation_fn()(h @ params["Wi"] + params["bi"])
        return x + (h @ params["Wo"] + params["bo"])
