"""Mixture-of-Experts FFN layer (expert parallelism).

Beyond-reference capability. Switch-transformer-style top-1 routing with a
fixed per-expert capacity so every shape is static under jit: tokens are
dispatched to [E, capacity, C] expert buffers with one einsum, each expert
runs a batched FFN (one [E,·,·] batched matmul pair → MXU), and results
combine back weighted by the router gate. Overflow tokens (beyond capacity)
pass through the residual unchanged — the standard capacity-drop policy.

Expert parallelism = sharding the leading E axis of the expert weights over
the mesh's ``model`` axis (see parallel/tp.py); XLA turns the dispatch
einsums into all-to-alls over ICI.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import initializers
from deeplearning4j_tpu.nn.config import LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


@register_layer("mixture_of_experts")
@dataclass
class MixtureOfExperts(LayerConfig):
    """Top-1 (switch) MoE over [B, T, C] token streams, residual style:
    ``y = x + combine(expert_ffn(dispatch(x)))``."""

    n_experts: int = 8
    ffn_mult: int = 4
    capacity_factor: float = 1.25
    activation: Any = "gelu"
    weight_init: Any = "xavier"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type, dtype=jnp.float32):
        C = input_type.size
        F = self.ffn_mult * C
        E = self.n_experts
        kg, ki, ko = jax.random.split(key, 3)
        init = lambda k, shape, fi, fo: initializers.initialize(
            self.weight_init, k, shape, fi, fo, dtype
        )
        return {
            "Wg": init(kg, (C, E), C, E),
            "Wi": jnp.stack([init(k, (C, F), C, F) for k in jax.random.split(ki, E)]),
            "bi": jnp.zeros((E, F), dtype),
            "Wo": jnp.stack([init(k, (F, C), F, C) for k in jax.random.split(ko, E)]),
            "bo": jnp.zeros((E, C), dtype),
        }

    def _capacity(self, n_tokens: int) -> int:
        cap = int(self.capacity_factor * n_tokens / self.n_experts)
        return max(cap, 1)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        B, T, C = x.shape
        E = self.n_experts
        N = B * T
        cap = self._capacity(N)
        xt = x.reshape(N, C)

        # Routing math runs in f32/int32 regardless of activation dtype:
        # a bf16 cumsum loses integer precision past 256 and collides slots.
        logits = (xt @ params["Wg"]).astype(jnp.float32)            # [N,E]
        gates = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(gates, axis=-1)             # [N]
        gate = jnp.max(gates, axis=-1).astype(x.dtype)  # [N]
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)       # [N,E]
        if mask is not None and mask.ndim >= 2:
            # padding tokens don't route: they must not consume expert
            # capacity (slots are position-ordered) nor receive expert output
            onehot = onehot * mask.reshape(N).astype(jnp.float32)[:, None]
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0             # slot per token
        keep = (pos >= 0) & (pos < cap)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32) * keep.astype(jnp.float32)[..., None]
        dispatch = (onehot[..., None] * slot).astype(x.dtype)       # [N,E,cap]

        xe = jnp.einsum("nec,nd->ecd", dispatch, xt)    # [E,cap,C]
        he = self.activation_fn()(jnp.einsum("ecd,edf->ecf", xe, params["Wi"]) + params["bi"][:, None])
        ye = jnp.einsum("ecf,efd->ecd", he, params["Wo"]) + params["bo"][:, None]
        combine = dispatch * gate[:, None, None]        # gate-weighted routes
        yt = jnp.einsum("nec,ecd->nd", combine, ye)
        return x + yt.reshape(B, T, C), state

    def load_balance_loss(self, params, x) -> jax.Array:
        """Auxiliary load-balancing loss (Switch §2.2): E · Σ_e f_e · P_e."""
        N = x.shape[0] * x.shape[1]
        logits = (x.reshape(N, -1) @ params["Wg"]).astype(jnp.float32)
        gates = jax.nn.softmax(logits, axis=-1)
        frac = jnp.mean(jax.nn.one_hot(jnp.argmax(gates, -1), self.n_experts), axis=0)
        prob = jnp.mean(gates, axis=0)
        return self.n_experts * jnp.sum(frac * prob)
