"""Layer library.

One config dataclass per layer type, registered for JSON serde. Coverage
targets the reference's nn/conf/layers/ set (~45 classes, SURVEY.md §2.1).
"""

from deeplearning4j_tpu.nn.layers.core import (
    ActivationLayer,
    AlphaDropout,
    AutoEncoder,
    Dense,
    DropoutLayer,
    ELULayer,
    Embedding,
    EmbeddingSequence,
    GaussianDropout,
    GaussianNoise,
    LeakyReLULayer,
    LossLayer,
    OutputLayer,
    Permute,
    PReLU,
    RepeatVector,
    SpatialDropout,
    ThresholdedReLULayer,
)
from deeplearning4j_tpu.nn.layers.convolution import (
    Conv1D,
    Conv2D,
    Cropping1D,
    Cropping2D,
    Deconv2D,
    DepthToSpace,
    DepthwiseConv2D,
    SeparableConv2D,
    SpaceToDepth,
    Subsampling1D,
    Subsampling2D,
    Upsampling1D,
    Upsampling2D,
    ZeroPadding1D,
    ZeroPadding2D,
)
from deeplearning4j_tpu.nn.layers.normalization import BatchNorm, LayerNorm, LocalResponseNormalization
from deeplearning4j_tpu.nn.layers.attention import (
    MultiHeadAttention,
    PositionalEmbedding,
    TransformerBlock,
)
from deeplearning4j_tpu.nn.layers.moe import MixtureOfExperts
from deeplearning4j_tpu.nn.layers.variational import VariationalAutoencoder
from deeplearning4j_tpu.nn.layers.objdetect import (
    Yolo2OutputLayer,
    get_predicted_objects,
    non_max_suppression,
)
from deeplearning4j_tpu.nn.layers.custom import (
    CenterLossOutputLayer,
    CnnLossLayer,
    CustomLayer,
    FrozenLayer,
    LambdaLayer,
)
from deeplearning4j_tpu.nn.layers.pooling import GlobalPooling
from deeplearning4j_tpu.nn.layers.recurrent import (
    Bidirectional,
    BidirectionalLastTimeStep,
    GravesLSTM,
    GRU,
    LastTimeStep,
    LSTM,
    MaskZero,
    RnnOutputLayer,
    SimpleRnn,
)

__all__ = [
    "ActivationLayer",
    "AlphaDropout",
    "EmbeddingSequence",
    "GaussianDropout",
    "GaussianNoise",
    "AutoEncoder",
    "Dense",
    "DropoutLayer",
    "Embedding",
    "LossLayer",
    "OutputLayer",
    "Conv1D",
    "Conv2D",
    "Deconv2D",
    "DepthwiseConv2D",
    "SeparableConv2D",
    "SpatialDropout",
    "Subsampling1D",
    "Subsampling2D",
    "Upsampling1D",
    "Upsampling2D",
    "ZeroPadding1D",
    "ZeroPadding2D",
    "Cropping1D",
    "BatchNorm",
    "LayerNorm",
    "MultiHeadAttention",
    "PositionalEmbedding",
    "TransformerBlock",
    "MixtureOfExperts",
    "VariationalAutoencoder",
    "Yolo2OutputLayer",
    "get_predicted_objects",
    "non_max_suppression",
    "CenterLossOutputLayer",
    "CnnLossLayer",
    "CustomLayer",
    "FrozenLayer",
    "LambdaLayer",
    "LocalResponseNormalization",
    "GlobalPooling",
    "Bidirectional",
    "GravesLSTM",
    "LastTimeStep",
    "LSTM",
    "MaskZero",
    "RnnOutputLayer",
    "SimpleRnn",
]
