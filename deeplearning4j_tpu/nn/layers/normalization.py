"""Normalization layers: BatchNorm, LRN.

Reference parity: nn/conf/layers/BatchNormalization.java +
nn/layers/normalization/{BatchNormalization,LocalResponseNormalization}.java
and their cuDNN helpers (CudnnBatchNormalizationHelper.java). On TPU these
are plain fused elementwise/reduction graphs; running statistics live in the
non-trainable ``state`` pytree (the flax ``batch_stats`` pattern) rather
than being updated in-place.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.config import LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


@register_layer("batch_norm")
@dataclass
class BatchNorm(LayerConfig):
    """Batch normalization over the channel/feature axis (last axis, NHWC).

    DL4J defaults (BatchNormalization.java): decay=0.9 ('momentum' of the
    running stats EMA), eps=1e-5, lockGammaBeta=False.
    """

    CONSUMES_EXAMPLE_WEIGHT = True  # batch stats must exclude padded rows

    decay: float = 0.9
    eps: float = 1e-5
    use_gamma_beta: bool = True   # lockGammaBeta=True in DL4J means fixed 1/0
    gamma_init: float = 1.0
    beta_init: float = 0.0

    def _nfeat(self, input_type: InputType) -> int:
        return input_type.channels if input_type.kind == "conv" else input_type.flat_size()

    def init(self, key, input_type, dtype=jnp.float32):
        n = self._nfeat(input_type)
        if not self.use_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((n,), self.gamma_init, dtype),
            "beta": jnp.full((n,), self.beta_init, dtype),
        }

    def init_state(self, input_type: InputType):
        n = self._nfeat(input_type)
        return {
            "mean": jnp.zeros((n,), jnp.float32),
            "var": jnp.ones((n,), jnp.float32),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              ex_weight=None):
        # Statistics in f32 (bf16 means/variances lose mantissa over real
        # batch sizes), but the NORMALIZATION is a per-channel scale/shift
        # folded to two [C] vectors and applied in the input dtype — so for
        # bf16 models the full activation tensor is never upcast and the
        # residuals XLA saves for backward stay bf16 (half the HBM traffic
        # of normalizing in f32).
        dt = x.dtype
        f32 = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis
        if train:
            # Shifted two-pass statistics with f32 ACCUMULATION but no f32
            # copy of the tensor in the autodiff graph: the mean is an
            # f32-accumulated reduction of x, the variance an f32-accumulated
            # reduction of the model-dtype residual squared — backward stays
            # in the model dtype, and the shifted form avoids the E[x^2]
            # cancellation that breaks channels with |mean| >> std. Both
            # branches use the same form so the DP-padded weighted step
            # reproduces the unpadded single-device statistics exactly.
            if ex_weight is not None:
                # Example-weighted statistics: rows with weight 0 (the
                # ParallelWrapper padding rows) contribute nothing to
                # mean/var. 0/1 weights are exact in every dtype, so casting
                # w to the model dtype keeps the math bit-equal while
                # avoiding an f32 promotion of x.
                w = ex_weight.reshape((x.shape[0],) + (1,) * (x.ndim - 1)).astype(dt)
                spatial = 1
                for d in x.shape[1:-1]:
                    spatial *= d
                denom = jnp.maximum(
                    jnp.sum(w, dtype=f32) * spatial, jnp.asarray(1.0, f32))
                mean = jnp.sum(x * w, axis=axes, dtype=f32) / denom
                xc = (x - mean.astype(dt)) * w
                var = jnp.sum(xc * xc, axis=axes, dtype=f32) / denom
            else:
                mean = jnp.mean(x, axis=axes, dtype=f32)
                xc = x - mean.astype(dt)
                var = jnp.mean(xc * xc, axis=axes, dtype=f32)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean,
                "var": self.decay * state["var"] + (1.0 - self.decay) * var,
            }
        else:
            mean, var = state["mean"].astype(f32), state["var"].astype(f32)
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        if self.use_gamma_beta and params:
            a = params["gamma"].astype(f32) * inv
            b = params["beta"].astype(f32) - mean * a
        else:
            a = inv
            b = -mean * inv
        y = x * a.astype(dt) + b.astype(dt)
        return y, new_state


@register_layer("lrn")
@dataclass
class LocalResponseNormalization(LayerConfig):
    """Local response normalization across channels (LocalResponseNormalization.java).

    DL4J defaults: k=2, n=5, alpha=1e-4, beta=0.75.
    """

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # Sum x^2 over a window of `n` adjacent channels (last axis, NHWC).
        half = self.n // 2
        sq = x * x
        # reduce_window over channel axis
        window = (1,) * (x.ndim - 1) + (self.n,)
        strides = (1,) * x.ndim
        pads = tuple(
            (0, 0) if i < x.ndim - 1 else (half, self.n - 1 - half) for i in range(x.ndim)
        )
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pads)
        denom = (self.k + self.alpha * ssum) ** self.beta
        return x / denom, state


def layer_norm(x, gamma=None, beta=None, eps: float = 1e-5):
    """Functional layer norm over the last axis (shared by LayerNorm and
    TransformerBlock). Statistics in f32 for bf16 inputs (stability), result
    cast back to the input dtype."""
    dt = x.dtype
    xs = x.astype(jnp.float32) if dt == jnp.bfloat16 else x
    mean = jnp.mean(xs, axis=-1, keepdims=True)
    var = jnp.mean((xs - mean) ** 2, axis=-1, keepdims=True)
    y = (xs - mean) * lax.rsqrt(var + eps)
    y = y.astype(dt)
    if gamma is not None:
        y = y * gamma + beta
    return y


@register_layer("layer_norm")
@dataclass
class LayerNorm(LayerConfig):
    """Layer normalization over the last (feature) axis.

    Beyond-reference capability (the reference has no transformer stack);
    required by the attention/transformer layers (attention.py). One fused
    reduce+elementwise graph under XLA.
    """

    eps: float = 1e-5
    use_gamma_beta: bool = True

    def _nfeat(self, input_type: InputType) -> int:
        return input_type.channels if input_type.kind == "conv" else input_type.size

    def init(self, key, input_type, dtype=jnp.float32):
        if not self.use_gamma_beta:
            return {}
        n = self._nfeat(input_type)
        return {"gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        g = params.get("gamma") if params else None
        b = params.get("beta") if params else None
        return layer_norm(x, g, b, self.eps), state
