"""YOLOv2 object-detection output layer + box utilities.

Capability parity with the reference's
nn/conf/layers/objdetect/Yolo2OutputLayer.java +
nn/layers/objdetect/Yolo2OutputLayer.java:71 and YoloUtils (box decoding,
IOU, non-max suppression). TPU-first: the loss is one fused graph over the
[B, H, W, A*(5+C)] prediction grid (NHWC — the reference uses NCHW);
NMS runs host-side on decoded detections (it is inference-only plumbing).

Label format (same capability as the reference's): [B, H, W, 4 + C] per-cell
ground truth: (x1, y1, x2, y2) in GRID units + one-hot class, with an
objectness indicator derived from the class vector (cells with no object are
all-zero).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.config import LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


@register_layer("yolo2_output")
@dataclass
class Yolo2OutputLayer(LayerConfig):
    """YOLOv2 loss head. ``boxes``: anchor priors [(w, h), ...] in grid units.

    lambda_coord / lambda_no_obj follow the reference defaults (5.0, 0.5).
    """

    CONSUMES_CONV = True  # takes [b,h,w,c] natively (no auto-flatten)

    boxes: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),)
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    @property
    def n_anchors(self) -> int:
        return len(self.boxes)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x, state  # raw grid passes through; decode via yolo_activate

    # -- decoding ----------------------------------------------------------
    def _split_grid(self, x, n_classes):
        """[B,H,W,A*(5+C)] -> (xy, wh, conf, class_logits)."""
        B, H, W, _ = x.shape
        A = self.n_anchors
        g = x.reshape(B, H, W, A, 5 + n_classes)
        return g[..., 0:2], g[..., 2:4], g[..., 4], g[..., 5:]

    def activate(self, x, n_classes):
        """Network output -> interpretable grid: sigmoid xy offsets, anchor-
        scaled wh, sigmoid objectness, softmax class probs (YoloUtils.activate)."""
        xy, wh, conf, cls = self._split_grid(x, n_classes)
        anchors = jnp.asarray(self.boxes, x.dtype)  # [A,2]
        return (
            jax.nn.sigmoid(xy),
            jnp.exp(wh) * anchors,
            jax.nn.sigmoid(conf),
            jax.nn.softmax(cls, axis=-1),
        )

    # -- loss --------------------------------------------------------------
    def score(self, params, x, labels, mask=None, average=True, weights=None):
        """YOLOv2 composite loss (Yolo2OutputLayer.computeScore equivalent):
        coord (xy + sqrt-wh) on responsible anchors, objectness MSE toward
        the TRUE IOU of the decoded predicted box vs ground truth
        (positives, Yolo2OutputLayer.java:71) / 0 (negatives), class
        cross-entropy on object cells. Anchor responsibility uses shape-IOU
        against the anchor PRIORS (centers cancel for priors anchored at the
        gt cell) — the true-IOU target uses decoded centers."""
        n_classes = labels.shape[-1] - 4
        B, H, W, _ = labels.shape
        A = self.n_anchors

        gt_box = labels[..., :4]                    # [B,H,W,4] grid units
        gt_cls = labels[..., 4:]                    # [B,H,W,C]
        obj = (jnp.sum(gt_cls, axis=-1) > 0).astype(x.dtype)  # [B,H,W]

        pxy, pwh, pconf, pcls = self.activate(x, n_classes)

        # ground-truth center/size in grid units, offsets within the cell
        gt_cxy = (gt_box[..., 0:2] + gt_box[..., 2:4]) / 2.0
        gt_wh = jnp.maximum(gt_box[..., 2:4] - gt_box[..., 0:2], 1e-6)
        gt_off = gt_cxy - jnp.floor(gt_cxy)

        # responsible anchor: shape-IOU between the anchor PRIORS and the gt
        # box (both centered) — selection only, no gradients flow through it
        anchors = jnp.asarray(self.boxes, x.dtype)              # [A,2]
        a_inter = (jnp.minimum(anchors[:, 0], gt_wh[..., None, 0])
                   * jnp.minimum(anchors[:, 1], gt_wh[..., None, 1]))
        a_union = (anchors[:, 0] * anchors[:, 1]
                   + (gt_wh[..., 0] * gt_wh[..., 1])[..., None] - a_inter)
        anchor_iou = a_inter / jnp.maximum(a_union, 1e-9)       # [B,H,W,A]
        resp = jax.nn.one_hot(jnp.argmax(anchor_iou, axis=-1), A, dtype=x.dtype)
        resp = resp * obj[..., None]

        # TRUE IOU of each anchor's decoded box vs gt: centers decoded as
        # cell corner + sigmoid offset, in absolute grid units
        cell_x = jnp.arange(W, dtype=x.dtype)[None, None, :, None]
        cell_y = jnp.arange(H, dtype=x.dtype)[None, :, None, None]
        pcx = cell_x + pxy[..., 0]                              # [B,H,W,A]
        pcy = cell_y + pxy[..., 1]
        px1, px2 = pcx - pwh[..., 0] / 2, pcx + pwh[..., 0] / 2
        py1, py2 = pcy - pwh[..., 1] / 2, pcy + pwh[..., 1] / 2
        ix = jnp.maximum(
            jnp.minimum(px2, gt_box[..., None, 2]) - jnp.maximum(px1, gt_box[..., None, 0]),
            0.0)
        iy = jnp.maximum(
            jnp.minimum(py2, gt_box[..., None, 3]) - jnp.maximum(py1, gt_box[..., None, 1]),
            0.0)
        inter = ix * iy
        union = (pwh[..., 0] * pwh[..., 1]
                 + (gt_wh[..., 0] * gt_wh[..., 1])[..., None] - inter)
        true_iou = inter / jnp.maximum(union, 1e-9)             # [B,H,W,A]

        coord = jnp.sum(
            resp
            * (
                jnp.sum((pxy - gt_off[..., None, :]) ** 2, axis=-1)
                + jnp.sum((jnp.sqrt(pwh) - jnp.sqrt(gt_wh)[..., None, :]) ** 2, axis=-1)
            )
        )
        # (pconf - IOU)^2 is kept fully differentiable: the loss is a single
        # consistent objective (so the f64 central-difference gradcheck holds
        # exactly), and the extra d(IOU)/d(box) term only nudges boxes toward
        # agreement with their own confidence — darknet's stop-gradient
        # variant is the limit where that term is dropped
        conf_pos = jnp.sum(resp * (pconf - true_iou) ** 2)
        conf_neg = jnp.sum((1.0 - resp) * pconf**2)
        cls_loss = -jnp.sum(
            obj[..., None] * gt_cls * jnp.log(jnp.maximum(
                jnp.sum(resp[..., None] * pcls, axis=3), 1e-9))
        )
        total = (self.lambda_coord * coord + conf_pos
                 + self.lambda_no_obj * conf_neg + cls_loss)
        if average:
            return total / B
        return total


def iou_xyxy(a: np.ndarray, b: np.ndarray) -> float:
    """IOU of two (x1,y1,x2,y2) boxes (YoloUtils.iou)."""
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


class DetectedObject:
    """One decoded detection (nn/layers/objdetect/DetectedObject.java)."""

    def __init__(self, box, confidence, class_idx, class_probs):
        self.box = box  # (x1,y1,x2,y2) in grid units
        self.confidence = float(confidence)
        self.class_idx = int(class_idx)
        self.class_probs = class_probs

    def __repr__(self):
        return f"DetectedObject(cls={self.class_idx}, conf={self.confidence:.3f}, box={self.box})"


def get_predicted_objects(layer: Yolo2OutputLayer, grid_out, n_classes: int,
                          threshold: float = 0.5) -> List[List[DetectedObject]]:
    """Decode network output into per-image detections above ``threshold``
    (YoloUtils.getPredictedObjects)."""
    pxy, pwh, pconf, pcls = (np.asarray(t) for t in layer.activate(jnp.asarray(grid_out), n_classes))
    B, H, W, A = pconf.shape
    out: List[List[DetectedObject]] = []
    for b in range(B):
        dets: List[DetectedObject] = []
        for i in range(H):
            for j in range(W):
                for a in range(A):
                    conf = pconf[b, i, j, a]
                    if conf < threshold:
                        continue
                    cx = j + pxy[b, i, j, a, 0]
                    cy = i + pxy[b, i, j, a, 1]
                    w, h = pwh[b, i, j, a]
                    box = (cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2)
                    probs = pcls[b, i, j, a]
                    dets.append(DetectedObject(box, conf, int(np.argmax(probs)), probs))
        out.append(dets)
    return out


def non_max_suppression(dets: List[DetectedObject], iou_threshold: float = 0.45
                        ) -> List[DetectedObject]:
    """Greedy class-wise NMS (YoloUtils.nms)."""
    keep: List[DetectedObject] = []
    for cls in {d.class_idx for d in dets}:
        cand = sorted((d for d in dets if d.class_idx == cls),
                      key=lambda d: -d.confidence)
        while cand:
            best = cand.pop(0)
            keep.append(best)
            cand = [d for d in cand
                    if iou_xyxy(np.asarray(best.box), np.asarray(d.box)) < iou_threshold]
    return keep
