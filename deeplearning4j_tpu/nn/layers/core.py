"""Core feed-forward layers: Dense, Output, Activation, Dropout, Embedding,
AutoEncoder.

Reference parity: nn/conf/layers/{DenseLayer,OutputLayer,ActivationLayer,
DropoutLayer,EmbeddingLayer,AutoEncoder}.java and their impls under
nn/layers/ (e.g. feedforward/embedding/EmbeddingLayer.java). Forward math is
a single fused matmul+bias+activation per layer; backward comes from
autodiff of the whole step (no per-layer backpropGradient).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import initializers, losses
from deeplearning4j_tpu.nn.config import FeedForwardLayerConfig, LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


@register_layer("dense")
@dataclass
class Dense(FeedForwardLayerConfig):
    """Fully connected layer: act(x @ W + b).

    Parity: nn/conf/layers/DenseLayer.java. Accepts rank-2 [batch, feat] or
    rank-3 [batch, time, feat] input (the reference inserts preprocessors for
    the latter; here the matmul is batched over leading axes natively, which
    XLA maps onto the MXU in one pass).
    """

    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {
            "W": initializers.initialize(self.weight_init, kW, (n_in, self.n_out), n_in, self.n_out, dtype)
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state

    def preactivation(self, params, x):
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y


@register_layer("output")
@dataclass
class OutputLayer(Dense):
    """Dense + loss head. Parity: nn/conf/layers/OutputLayer.java.

    The model computes the loss via :meth:`score` on the PRE-activation so the
    (softmax, mcxent) pair is fused into a stable log-softmax form
    (losses.per_example_scores).
    """

    loss: Any = "mcxent"

    def score(self, params, x, labels, mask=None, average=True, weights=None):
        preact = self.preactivation(params, x)
        act = getattr(self, "activation", "identity")
        if average:
            return losses.average_score(self.loss, labels, preact, act, mask, weights)
        return losses.per_example_scores(self.loss, labels, preact, act, mask, weights)


@register_layer("loss")
@dataclass
class LossLayer(LayerConfig):
    """Parameter-free loss head (LossLayer.java): applies activation + loss to
    its input unchanged."""

    activation: Any = "identity"
    loss: Any = "mcxent"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn()(x), state

    def score(self, params, x, labels, mask=None, average=True, weights=None):
        if average:
            return losses.average_score(self.loss, labels, x, self.activation, mask, weights)
        return losses.per_example_scores(self.loss, labels, x, self.activation, mask, weights)


@register_layer("activation")
@dataclass
class ActivationLayer(LayerConfig):
    """Standalone activation (ActivationLayer.java)."""

    activation: Any = "relu"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn()(x), state


@register_layer("leaky_relu_layer")
@dataclass
class LeakyReLULayer(LayerConfig):
    """Parameterized leaky ReLU (Keras LeakyReLU / nd4j ActivationLReLU with
    a configurable slope — the registry 'leakyrelu' is fixed at 0.01)."""

    alpha: float = 0.3

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jax.nn.leaky_relu(x, negative_slope=self.alpha), state


@register_layer("elu_layer")
@dataclass
class ELULayer(LayerConfig):
    """Parameterized ELU (Keras ELU / nd4j ActivationELU(alpha))."""

    alpha: float = 1.0

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.where(x > 0, x, self.alpha * (jnp.exp(x) - 1.0)), state


@register_layer("thresholded_relu_layer")
@dataclass
class ThresholdedReLULayer(LayerConfig):
    """Keras ThresholdedReLU: x if x > theta else 0."""

    theta: float = 1.0

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.where(x > self.theta, x, 0.0), state


@register_layer("prelu")
@dataclass
class PReLU(LayerConfig):
    """PReLU with LEARNED negative slope (PReLULayer.java; Keras PReLU
    default: one alpha per non-batch element, initialized to zero)."""

    def init(self, key, input_type, dtype=jnp.float32):
        shape = input_type.batch_shape(1)[1:]
        return {"alpha": jnp.zeros(shape, dtype)}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        a = params["alpha"]
        return jnp.where(x >= 0, x, a * x), state


@register_layer("permute")
@dataclass
class Permute(LayerConfig):
    """Permute the non-batch axes (Keras Permute; DL4J PermutePreprocessor).
    ``dims``: 1-based permutation of the non-batch axes, Keras-style."""

    dims: Any = (1,)

    def _axes(self):
        return (0,) + tuple(int(d) for d in self.dims)

    def output_type(self, input_type: InputType) -> InputType:
        shape = input_type.batch_shape(1)[1:]
        new = tuple(shape[d - 1] for d in self.dims)
        if len(new) == 1:
            return InputType.feed_forward(new[0])
        if len(new) == 2:
            return InputType.recurrent(new[1], new[0])
        if len(new) == 3:
            return InputType.convolutional(new[0], new[1], new[2])
        raise ValueError(f"Permute: unsupported rank {len(new)}")

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.transpose(x, self._axes()), state


@register_layer("repeat_vector")
@dataclass
class RepeatVector(LayerConfig):
    """[B,F] -> [B,n,F] (RepeatVector.java / Keras RepeatVector)."""

    n: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.flat_size(), self.n)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state


@register_layer("dropout")
@dataclass
class DropoutLayer(LayerConfig):
    """Standalone inverted dropout (DropoutLayer.java / conf/dropout/Dropout).

    `dropout` is the DROP probability, DL4J-style; identity at inference.
    """

    dropout: float = 0.5

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.maybe_dropout_input(x, train, rng), state


@register_layer("spatial_dropout")
@dataclass
class SpatialDropout(LayerConfig):
    """Channel-wise (spatial) dropout (conf/dropout/SpatialDropout.java):
    drops ENTIRE feature maps — one Bernoulli draw per [batch, channel],
    broadcast over the spatial/temporal axes. Inverted scaling, identity at
    inference. Works on [B,H,W,C] (SpatialDropout2D) and [B,T,C]
    (SpatialDropout1D) inputs alike: every axis between batch and channel
    is broadcast."""

    dropout: float = 0.5

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        p = float(self.dropout)
        if not train or p <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("SpatialDropout requires an rng key in training mode")
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, 1.0 - p, shape)
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype), state


@register_layer("gaussian_noise")
@dataclass
class GaussianNoise(LayerConfig):
    """Additive gaussian noise (conf/dropout/GaussianNoise.java)."""

    stddev: float = 0.1

    def uses_rng(self) -> bool:
        return super().uses_rng() or self.stddev > 0.0

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if not train or self.stddev <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("GaussianNoise requires an rng key in training mode")
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype), state


@register_layer("gaussian_dropout")
@dataclass
class GaussianDropout(LayerConfig):
    """Multiplicative gaussian noise (conf/dropout/GaussianDropout.java):
    x * N(1, rate/(1-rate))."""

    rate: float = 0.5

    def uses_rng(self) -> bool:
        return super().uses_rng() or self.rate > 0.0

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if not train or self.rate <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("GaussianDropout requires an rng key in training mode")
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + std * jax.random.normal(rng, x.shape, x.dtype)), state


@register_layer("alpha_dropout")
@dataclass
class AlphaDropout(LayerConfig):
    """SELU-preserving dropout (conf/dropout/AlphaDropout.java)."""

    dropout: float = 0.5

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if not train or self.dropout <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("AlphaDropout requires an rng key in training mode")
        p_keep = 1.0 - self.dropout
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(rng, p_keep, x.shape)
        a = (p_keep + alpha_p**2 * p_keep * (1 - p_keep)) ** -0.5
        b = -a * alpha_p * (1 - p_keep)
        return a * jnp.where(keep, x, alpha_p) + b, state


@register_layer("embedding")
@dataclass
class Embedding(FeedForwardLayerConfig):
    """Embedding lookup (feedforward/embedding/EmbeddingLayer.java): input is
    integer indices [batch] or [batch, 1]; output [batch, n_out].

    TPU note: lookup is a gather (one-hot matmul for tiny vocabularies would
    also hit the MXU, but XLA's gather is fine here); backward produces a
    scatter-add, which XLA handles natively — no special 'embedding updater'.
    """

    has_bias: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {
            "W": initializers.initialize(self.weight_init, kW, (n_in, self.n_out), n_in, self.n_out, dtype)
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer("embedding_sequence")
@dataclass
class EmbeddingSequence(FeedForwardLayerConfig):
    """Sequence embedding: int [batch, time] -> [batch, time, n_out]."""

    has_bias: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        kW, _ = jax.random.split(key)
        return {
            "W": initializers.initialize(self.weight_init, kW, (n_in, self.n_out), n_in, self.n_out, dtype)
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        return jnp.take(params["W"], idx, axis=0), state


@register_layer("autoencoder")
@dataclass
class AutoEncoder(FeedForwardLayerConfig):
    """Denoising autoencoder layer (conf/layers/AutoEncoder.java).

    Supervised-path behavior matches the reference: acts as a Dense encoder.
    :meth:`reconstruct` exposes encode→decode with tied-ish params (separate
    decoder weights, like the reference's w/vb params); corruption_level is
    the input-corruption fraction used during unsupervised pretraining.
    """

    corruption_level: float = 0.3
    activation: Any = "sigmoid"

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        kW, kV = jax.random.split(key)
        return {
            "W": initializers.initialize(self.weight_init, kW, (n_in, self.n_out), n_in, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.zeros((n_in,), dtype),  # visible bias for the decode path
        }

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        return self.activation_fn()(x @ params["W"] + params["b"]), state

    def encode(self, params, x):
        return self.activation_fn()(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self.activation_fn()(h @ params["W"].T + params["vb"])

    def reconstruct(self, params, x, *, rng=None, corrupt=False):
        if corrupt and rng is not None and self.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            x = jnp.where(keep, x, 0.0)
        return self.decode(params, self.encode(params, x))
