"""Custom-layer API + misc heads: the SameDiff-layer equivalent, LambdaLayer,
FrozenLayer, CenterLossOutputLayer, CnnLossLayer.

Reference parity:
- nn/conf/layers/samediff/AbstractSameDiffLayer.java + SameDiffLayer.java —
  user-defined layers. Here the whole framework already IS "define forward,
  autodiff the rest", so the custom-layer API is just the LayerConfig
  contract: subclass ``CustomLayer``, implement ``init``/``forward``,
  decorate with ``@register_layer`` for JSON serde.
- SameDiffLambdaLayer → ``LambdaLayer`` (stateless function).
- nn/conf/layers/misc/FrozenLayer.java → ``FrozenLayer`` wrapper.
- nn/conf/layers/CenterLossOutputLayer.java → ``CenterLossOutputLayer``.
- nn/conf/layers/CnnLossLayer.java → ``CnnLossLayer``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import initializers, losses
from deeplearning4j_tpu.nn.config import FeedForwardLayerConfig, LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


class CustomLayer(LayerConfig):
    """Base class for user-defined layers (SameDiff-layer equivalent).

    Subclass, implement ``init`` (params pytree) and ``forward`` (pure
    function of (params, x)); backward is autodiff. Register with
    ``@register_layer("my_type")`` to make configs JSON round-trippable.
    """

    def forward(self, params, x):
        raise NotImplementedError

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.forward(params, x), state


@register_layer("lambda")
@dataclass
class LambdaLayer(LayerConfig):
    """Stateless function layer (SameDiffLambdaLayer equivalent). The
    function does not survive JSON round-trips (same limitation as the
    reference, which needs the class on the classpath)."""

    fn: Optional[Callable] = None

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if self.fn is None:
            raise ValueError("LambdaLayer.fn missing (not restorable from JSON)")
        return self.fn(x), state


@register_layer("frozen")
@dataclass
class FrozenLayer(LayerConfig):
    """Wrapper excluding the inner layer's params from training
    (nn/conf/layers/misc/FrozenLayer.java). Equivalent to
    ``dataclasses.replace(inner, trainable=False)`` — provided for API parity
    with transfer learning surgery."""

    inner: Optional[LayerConfig] = None

    def __post_init__(self):
        self.trainable = False

    def output_type(self, input_type: InputType) -> InputType:
        return self.inner.output_type(input_type)

    def init(self, key, input_type, dtype=jnp.float32):
        return self.inner.init(key, input_type, dtype)

    def init_state(self, input_type: InputType):
        return self.inner.init_state(input_type)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # inference-mode inner apply: frozen layers don't update BN stats etc.
        y, _ = self.inner.apply(params, state, x, train=False, rng=rng, mask=mask)
        return y, state

    def propagate_mask(self, mask, input_type):
        return self.inner.propagate_mask(mask, input_type)

    def score(self, params, x, labels, mask=None, average=True, weights=None):
        return self.inner.score(params, x, labels, mask=mask, average=average, weights=weights)


@register_layer("center_loss_output")
@dataclass
class CenterLossOutputLayer(FeedForwardLayerConfig):
    """Softmax output + center loss (CenterLossOutputLayer.java): pulls each
    example's PRE-output features toward its class center.

    ``alpha`` scales the center-update speed; here centers are parameters
    whose gradient from the center term is exactly the (feature - center)
    EMA direction the reference applies by hand, so plain SGD/Adam on them
    reproduces the behavior. ``lambda_`` weights the center term.
    """

    alpha: float = 0.05
    lambda_: float = 2e-4
    loss: Any = "mcxent"
    activation: Any = "softmax"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        kW, _ = jax.random.split(key)
        return {
            "W": initializers.initialize(self.weight_init, kW, (n_in, self.n_out), n_in, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "centers": jnp.zeros((self.n_out, n_in), dtype),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y = x @ params["W"] + params["b"]
        return self.activation_fn()(y), state

    def score(self, params, x, labels, mask=None, average=True, weights=None):
        preact = x @ params["W"] + params["b"]
        base = losses.average_score(self.loss, labels, preact, self.activation, mask, weights)
        if jnp.asarray(labels).ndim == preact.ndim - 1:
            # sparse integer labels index their centers directly
            centers_for = params["centers"][jnp.asarray(labels).astype(jnp.int32)]
        else:
            centers_for = labels @ params["centers"]  # one-hot picks rows
        center_term = 0.5 * jnp.mean(jnp.sum((x - centers_for) ** 2, axis=-1))
        # alpha folds into the centers' learning rate via the term scale
        return base + self.lambda_ * self.alpha / 0.05 * center_term

    BIAS_PARAM_NAMES = frozenset({"b", "centers"})  # centers: no l1/l2


@register_layer("cnn_loss")
@dataclass
class CnnLossLayer(LayerConfig):
    """Per-pixel loss head for dense prediction / segmentation
    (CnnLossLayer.java): activation + loss applied at every spatial position
    of [B, H, W, C]; 2D masks broadcast over channels."""

    CONSUMES_CONV = True  # takes [b,h,w,c] natively (no auto-flatten)

    activation: Any = "identity"
    loss: Any = "mcxent"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn()(x), state

    def score(self, params, x, labels, mask=None, average=True, weights=None):
        B, H, W, C = x.shape
        flat_x = x.reshape(B * H * W, C)
        flat_y = labels.reshape(B * H * W, C)
        flat_m = mask.reshape(-1) if mask is not None else None
        if average:
            return losses.average_score(self.loss, flat_y, flat_x, self.activation, flat_m, weights)
        per = losses.per_example_scores(self.loss, flat_y, flat_x, self.activation, flat_m, weights)
        return per.reshape(B, H, W)
