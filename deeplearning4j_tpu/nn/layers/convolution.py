"""Convolution / pooling / padding / upsampling layers (NHWC).

Reference parity: nn/conf/layers/{ConvolutionLayer,Convolution1DLayer,
Deconvolution2D,SeparableConvolution2D,DepthwiseConvolution2D,
SubsamplingLayer,Subsampling1DLayer,Upsampling2D,ZeroPaddingLayer}.java and
the cuDNN helpers they dispatch to
(/root/reference/deeplearning4j-cuda/.../CudnnConvolutionHelper.java:54,
CudnnSubsamplingHelper.java). On TPU all of these lower to
``lax.conv_general_dilated`` / ``lax.reduce_window``, which XLA tiles onto
the MXU — the helper indirection disappears (one lowering path, always on).

Layout: **NHWC** + HWIO kernels (the reference is NCHW; NHWC is what XLA:TPU
prefers). ``convolution_mode`` mirrors DL4J's Same/Truncate/Strict.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import initializers
from deeplearning4j_tpu.nn.config import FeedForwardLayerConfig, LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType

DIMNUMS = ("NHWC", "HWIO", "NHWC")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_size(size: int, k: int, s: int, p: int, mode: str, d: int = 1) -> int:
    k_eff = (k - 1) * d + 1  # effective kernel extent under dilation
    if mode == "same":
        return -(-size // s)  # ceil
    if mode == "strict":
        if (size - k_eff + 2 * p) % s != 0:
            raise ValueError(
                f"Strict convolution mode: ({size} - {k_eff} + 2*{p}) not divisible by stride {s}"
            )
    return (size - k_eff + 2 * p) // s + 1


def _conv_padding(mode: str, pad: Tuple[int, int]):
    if mode == "same":
        return "SAME"
    return [(pad[0], pad[0]), (pad[1], pad[1])]


@register_layer("conv2d")
@dataclass
class Conv2D(FeedForwardLayerConfig):
    """2-D convolution. Parity: nn/conf/layers/ConvolutionLayer.java.

    n_out = output channels; n_in inferred from input channels.
    """

    kernel: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    dilation: Any = (1, 1)
    convolution_mode: str = "truncate"  # same | truncate | strict
    has_bias: bool = True

    def infer_n_in(self, input_type):
        return input_type.channels  # n_in = input channels, not flat size

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind != "conv":
            raise ValueError(f"Conv2D needs convolutional input, got {input_type}")
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        oh = _out_size(input_type.height, kh, sh, ph, self.convolution_mode, dh)
        ow = _out_size(input_type.width, kw, sw, pw, self.convolution_mode, dw)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type, dtype=jnp.float32):
        in_c = self.n_in if self.n_in is not None else input_type.channels
        kh, kw = _pair(self.kernel)
        fan_in = in_c * kh * kw
        fan_out = self.n_out * kh * kw
        kW, _ = jax.random.split(key)
        params = {
            "W": initializers.initialize(
                self.weight_init, kW, (kh, kw, in_c, self.n_out), fan_in, fan_out, dtype
            )
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params

    def _conv(self, x, W, groups: int = 1):
        sh, sw = _pair(self.stride)
        # NOTE: a slice-then-dense rewrite of strided 1x1 convs (the
        # ResNet-v1 bottleneck pattern) was a +12% win in round 3 but a
        # -12% LOSS on the round-4 toolchain — the strided-gather lowering
        # improved and the explicit slice now breaks producer fusion. The
        # null-experiment A/B lives in docs/PERF.md; keep the plain form.
        return lax.conv_general_dilated(
            x,
            W,
            window_strides=(sh, sw),
            padding=_conv_padding(self.convolution_mode, _pair(self.padding)),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=DIMNUMS,
            feature_group_count=groups,
        )

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = self._conv(x, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state

    def propagate_mask(self, mask, input_type):
        return None  # masks don't flow through spatial convs


@register_layer("deconv2d")
@dataclass
class Deconv2D(Conv2D):
    """Transposed convolution (Deconvolution2D.java)."""

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == "same":
            oh, ow = input_type.height * sh, input_type.width * sw
        else:
            oh = sh * (input_type.height - 1) + kh - 2 * ph
            ow = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        kh, kw = _pair(self.kernel)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == "same":
            padding = "SAME"
        else:
            # lax.conv_transpose applies explicit pads to the dilated input;
            # (k-1-p, k-1-p) yields the standard deconv output size
            # s*(h-1) + k - 2p that output_type advertises.
            padding = [(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)]
        y = lax.conv_transpose(
            x,
            params["W"],
            strides=_pair(self.stride),
            padding=padding,
            dimension_numbers=DIMNUMS,
        )
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer("depthwise_conv2d")
@dataclass
class DepthwiseConv2D(Conv2D):
    """Depthwise convolution (DepthwiseConvolution2D.java): each input channel
    convolved with `depth_multiplier` filters; n_out = in_c * depth_multiplier."""

    depth_multiplier: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        base = super().output_type(
            input_type
        )
        return InputType.convolutional(base.height, base.width, input_type.channels * self.depth_multiplier)

    def init(self, key, input_type, dtype=jnp.float32):
        in_c = self.n_in if self.n_in is not None else input_type.channels
        kh, kw = _pair(self.kernel)
        out_c = in_c * self.depth_multiplier
        kW, _ = jax.random.split(key)
        params = {
            "W": initializers.initialize(
                self.weight_init, kW, (kh, kw, 1, out_c), kh * kw, kh * kw * self.depth_multiplier, dtype
            )
        }
        if self.has_bias:
            params["b"] = jnp.full((out_c,), self.bias_init, dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = self._conv(x, params["W"], groups=x.shape[-1])
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer("separable_conv2d")
@dataclass
class SeparableConv2D(Conv2D):
    """Depthwise + pointwise (SeparableConvolution2D.java)."""

    depth_multiplier: int = 1

    def init(self, key, input_type, dtype=jnp.float32):
        in_c = self.n_in if self.n_in is not None else input_type.channels
        kh, kw = _pair(self.kernel)
        mid_c = in_c * self.depth_multiplier
        kD, kP = jax.random.split(key)
        params = {
            "dW": initializers.initialize(
                self.weight_init, kD, (kh, kw, 1, mid_c), kh * kw, kh * kw, dtype
            ),
            "pW": initializers.initialize(
                self.weight_init, kP, (1, 1, mid_c, self.n_out), mid_c, self.n_out, dtype
            ),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = self._conv(x, params["dW"], groups=x.shape[-1])
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID", dimension_numbers=DIMNUMS
        )
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer("conv1d")
@dataclass
class Conv1D(FeedForwardLayerConfig):
    """1-D convolution over [batch, time, feat] (Convolution1DLayer.java)."""

    kernel: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        ot = None
        if t is not None:
            ot = _out_size(
                t,
                int(self.kernel),
                int(self.stride),
                int(self.padding),
                self.convolution_mode,
                int(self.dilation),
            )
        return InputType.recurrent(self.n_out, ot)

    def init(self, key, input_type, dtype=jnp.float32):
        in_c = self.n_in if self.n_in is not None else input_type.size
        k = int(self.kernel)
        kW, _ = jax.random.split(key)
        params = {
            "W": initializers.initialize(
                self.weight_init, kW, (k, in_c, self.n_out), k * in_c, k * self.n_out, dtype
            )
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        pad = (
            "SAME"
            if self.convolution_mode == "same"
            else [(int(self.padding), int(self.padding))]
        )
        y = lax.conv_general_dilated(
            x,
            params["W"],
            window_strides=(int(self.stride),),
            padding=pad,
            rhs_dilation=(int(self.dilation),),
            dimension_numbers=("NHC", "HIO", "NHC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state

    def propagate_mask(self, mask, input_type):
        return _subsample_mask_1d(
            mask, int(self.kernel), int(self.stride), int(self.padding),
            self.convolution_mode, int(self.dilation),
        )


def _subsample_mask_1d(mask, kernel, stride, padding, mode, dilation=1):
    """Downsample a [batch, T] mask to the pooled/conv output length: keep the
    mask value at each output window's start position (the reference's
    stride-based mask reduction for 1-D conv/subsampling layers)."""
    if mask is None:
        return None
    T = mask.shape[1]
    if mode == "same":
        ot = -(-T // stride)  # ceil
    else:
        ot = _out_size(T, kernel, stride, padding, mode, dilation)
    idx = jnp.clip(jnp.arange(ot) * stride, 0, T - 1)
    return jnp.take(mask, idx, axis=1)


@register_layer("subsampling2d")
@dataclass
class Subsampling2D(LayerConfig):
    """Spatial pooling (SubsamplingLayer.java): max | avg | sum | pnorm."""

    kernel: Any = (2, 2)
    stride: Any = (2, 2)
    padding: Any = (0, 0)
    pooling: str = "max"
    pnorm: int = 2
    convolution_mode: str = "truncate"

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        oh = _out_size(input_type.height, kh, sh, ph, self.convolution_mode)
        ow = _out_size(input_type.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(oh, ow, input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.convolution_mode == "same":
            pads = "SAME"
        else:
            pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        if self.pooling == "max":
            init = -jnp.inf
            y = lax.reduce_window(x, init, lax.max, window, strides, pads)
        elif self.pooling in ("avg", "mean"):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            y = s / (kh * kw)
        elif self.pooling == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        elif self.pooling == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pads)
            y = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling '{self.pooling}'")
        return y, state

    def propagate_mask(self, mask, input_type):
        return None


@register_layer("subsampling1d")
@dataclass
class Subsampling1D(LayerConfig):
    """Temporal pooling over [batch, time, feat] (Subsampling1DLayer.java)."""

    kernel: int = 2
    stride: int = 2
    padding: int = 0
    pooling: str = "max"
    pnorm: int = 2
    convolution_mode: str = "truncate"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        ot = None
        if t is not None:
            ot = _out_size(t, int(self.kernel), int(self.stride), int(self.padding), self.convolution_mode)
        return InputType.recurrent(input_type.size, ot)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        k, s, p = int(self.kernel), int(self.stride), int(self.padding)
        window = (1, k, 1)
        strides = (1, s, 1)
        pads = "SAME" if self.convolution_mode == "same" else ((0, 0), (p, p), (0, 0))
        if self.pooling == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        elif self.pooling in ("avg", "mean"):
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads) / k
        elif self.pooling == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        elif self.pooling == "pnorm":
            pn = float(getattr(self, "pnorm", 2))
            s_ = lax.reduce_window(jnp.abs(x) ** pn, 0.0, lax.add, window, strides, pads)
            y = s_ ** (1.0 / pn)
        else:
            raise ValueError(f"Unknown pooling '{self.pooling}'")
        return y, state

    def propagate_mask(self, mask, input_type):
        return _subsample_mask_1d(
            mask, int(self.kernel), int(self.stride), int(self.padding), self.convolution_mode
        )


@register_layer("upsampling2d")
@dataclass
class Upsampling2D(LayerConfig):
    """Nearest-neighbor upsampling (Upsampling2D.java)."""

    size: Any = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        sh, sw = _pair(self.size)
        return InputType.convolutional(input_type.height * sh, input_type.width * sw, input_type.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        y = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return y, state


@register_layer("upsampling1d")
@dataclass
class Upsampling1D(LayerConfig):
    """Temporal nearest-neighbor upsampling over [B,T,F]
    (Upsampling1D.java)."""

    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return InputType.recurrent(
            input_type.size, t * int(self.size) if t is not None else None)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x, int(self.size), axis=1), state

    def propagate_mask(self, mask, input_type):
        if mask is None:
            return None
        return jnp.repeat(mask, int(self.size), axis=1)


@register_layer("zero_padding1d")
@dataclass
class ZeroPadding1D(LayerConfig):
    """Temporal zero padding over [B,T,F] (ZeroPadding1DLayer.java).
    padding: (left, right) or symmetric int."""

    padding: Any = (1, 1)

    def _pads(self):
        p = self.padding
        if isinstance(p, (tuple, list)):
            return int(p[0]), int(p[1])
        return int(p), int(p)

    def output_type(self, input_type: InputType) -> InputType:
        l, r = self._pads()
        t = input_type.timesteps
        return InputType.recurrent(
            input_type.size, t + l + r if t is not None else None)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        l, r = self._pads()
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state

    def propagate_mask(self, mask, input_type):
        if mask is None:
            return None
        l, r = self._pads()
        return jnp.pad(mask, ((0, 0), (l, r)), constant_values=1.0)


@register_layer("cropping1d")
@dataclass
class Cropping1D(LayerConfig):
    """Temporal cropping over [B,T,F] (Cropping1D.java).
    crop: (left, right) or symmetric int."""

    crop: Any = (0, 0)

    def _crops(self):
        c = self.crop
        if isinstance(c, (tuple, list)):
            return int(c[0]), int(c[1])
        return int(c), int(c)

    def output_type(self, input_type: InputType) -> InputType:
        l, r = self._crops()
        t = input_type.timesteps
        return InputType.recurrent(
            input_type.size, t - l - r if t is not None else None)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        l, r = self._crops()
        t = x.shape[1]
        return x[:, l: t - r, :], state

    def propagate_mask(self, mask, input_type):
        if mask is None:
            return None
        l, r = self._crops()
        return mask[:, l: mask.shape[1] - r]


@register_layer("zero_padding2d")
@dataclass
class ZeroPadding2D(LayerConfig):
    """Explicit spatial zero padding (ZeroPaddingLayer.java).

    padding: (top, bottom, left, right) or (h, w) symmetric.
    """

    padding: Any = (1, 1, 1, 1)

    def _pads(self):
        p = self.padding
        if isinstance(p, (tuple, list)) and len(p) == 4:
            return tuple(int(v) for v in p)
        ph, pw = _pair(p)
        return (ph, ph, pw, pw)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self._pads()
        return InputType.convolutional(
            input_type.height + t + b, input_type.width + l + r, input_type.channels
        )

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_layer("cropping2d")
@dataclass
class Cropping2D(LayerConfig):
    """Spatial cropping (Cropping2D.java). crop: (top, bottom, left, right)."""

    CONSUMES_CONV = True

    crop: Any = (0, 0, 0, 0)

    def _crops(self):
        c = self.crop
        if isinstance(c, (tuple, list)) and len(c) == 4:
            return tuple(int(v) for v in c)
        ch, cw = _pair(c)
        return (ch, ch, cw, cw)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self._crops()
        return InputType.convolutional(
            input_type.height - t - b, input_type.width - l - r, input_type.channels
        )

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self._crops()
        h, w = x.shape[1], x.shape[2]
        return x[:, t : h - b, l : w - r, :], state


@register_layer("space_to_depth")
@dataclass
class SpaceToDepth(LayerConfig):
    """[B,H,W,C] -> [B,H/b,W/b,C*b^2] (SpaceToDepthLayer.java). On TPU this
    is also the MLPerf-style stem trick: it turns a thin-channel stem conv
    (C_in=3, which underfills the 128-lane MXU contraction) into a
    b^2-richer one."""

    CONSUMES_CONV = True

    block: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = int(self.block)
        if input_type.height % b or input_type.width % b:
            raise ValueError(
                f"SpaceToDepth: spatial dims {input_type.height}x"
                f"{input_type.width} not divisible by block {b}")
        return InputType.convolutional(
            input_type.height // b, input_type.width // b,
            input_type.channels * b * b)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b = int(self.block)
        B, H, W, C = x.shape
        y = x.reshape(B, H // b, b, W // b, b, C)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // b, W // b, b * b * C)
        return y, state


@register_layer("depth_to_space")
@dataclass
class DepthToSpace(LayerConfig):
    """[B,H,W,C*b^2] -> [B,H*b,W*b,C] (the inverse; Upsampling alternative)."""

    CONSUMES_CONV = True

    block: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = int(self.block)
        if input_type.channels % (b * b):
            raise ValueError(
                f"DepthToSpace: channels {input_type.channels} not divisible "
                f"by block^2 {b * b}")
        return InputType.convolutional(
            input_type.height * b, input_type.width * b,
            input_type.channels // (b * b))

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b = int(self.block)
        B, H, W, C = x.shape
        y = x.reshape(B, H, W, b, b, C // (b * b))
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(B, H * b, W * b, C // (b * b))
        return y, state
