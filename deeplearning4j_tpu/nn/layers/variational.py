"""Variational autoencoder layer.

Capability parity with the reference's
nn/conf/layers/variational/VariationalAutoencoder.java +
nn/layers/variational/VariationalAutoencoder.java:51 (encoder/decoder MLPs,
gaussian reparameterization, pluggable reconstruction distributions, ELBO
pretraining, reconstructionProbability / reconstructionLogProbability,
activate == mean of q(z|x) for the supervised path).

TPU-first: the whole ELBO (encoder, reparameterized sample, decoder,
KL + reconstruction log-prob) is one fused graph; ``pretrain_loss`` plugs
into the standard jitted step as the layer's score.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, initializers
from deeplearning4j_tpu.nn.config import FeedForwardLayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType

# math.log, NOT jnp.log: module-level jnp ops initialize the default JAX
# backend at import time, which breaks callers that need to configure the
# platform (e.g. a CPU mesh) before first use.
_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


@register_layer("vae")
@dataclass
class VariationalAutoencoder(FeedForwardLayerConfig):
    """VAE as a layer: supervised forward = posterior mean (the reference's
    activate(), VariationalAutoencoder.java:51); ``elbo_loss`` drives
    unsupervised pretraining.

    ``reconstruction``: "gaussian" (diagonal, learned variance) or
    "bernoulli" (sigmoid logits).
    n_out == size of the latent z; encoder/decoder_layer_sizes mirror
    encoderLayerSizes/decoderLayerSizes in the reference config.
    """

    encoder_layer_sizes: Tuple[int, ...] = (256,)
    decoder_layer_sizes: Tuple[int, ...] = (256,)
    reconstruction: str = "gaussian"
    pzx_activation: Any = "identity"
    num_samples: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _dims(self, n_in: int):
        enc = [n_in, *self.encoder_layer_sizes]
        dec = [self.n_out, *self.decoder_layer_sizes]
        rec_params_per_feat = 2 if self.reconstruction == "gaussian" else 1
        return enc, dec, rec_params_per_feat

    def init(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in if self.n_in is not None else input_type.flat_size()
        enc, dec, rpf = self._dims(n_in)
        keys = iter(jax.random.split(key, len(enc) + len(dec) + 2))
        mk = lambda fi, fo: initializers.initialize(
            self.weight_init, next(keys), (fi, fo), fi, fo, dtype
        )
        p: Dict[str, Any] = {"enc": [], "dec": []}
        for a, b in zip(enc[:-1], enc[1:]):
            p["enc"].append({"W": mk(a, b), "b": jnp.zeros((b,), dtype)})
        # q(z|x): mean + log-variance heads off the last encoder layer
        p["zW"] = mk(enc[-1], 2 * self.n_out)
        p["zb"] = jnp.zeros((2 * self.n_out,), dtype)
        for a, b in zip(dec[:-1], dec[1:]):
            p["dec"].append({"W": mk(a, b), "b": jnp.zeros((b,), dtype)})
        # p(x|z) distribution params
        p["xW"] = mk(dec[-1], rpf * n_in)
        p["xb"] = jnp.zeros((rpf * n_in,), dtype)
        return p

    # -- pieces ------------------------------------------------------------
    def _mlp(self, blocks, x):
        act = self.activation_fn()
        for blk in blocks:
            x = act(x @ blk["W"] + blk["b"])
        return x

    def encode(self, params, x) -> Tuple[jax.Array, jax.Array]:
        """q(z|x) → (mean, log_var)."""
        h = self._mlp(params["enc"], x)
        zp = h @ params["zW"] + params["zb"]
        mean, log_var = jnp.split(zp, 2, axis=-1)
        return activations.get(self.pzx_activation)(mean), log_var

    def decode(self, params, z) -> jax.Array:
        h = self._mlp(params["dec"], z)
        return h @ params["xW"] + params["xb"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        mean, _ = self.encode(params, x)
        return mean, state

    # -- ELBO pretraining --------------------------------------------------
    def _reconstruction_log_prob(self, params, x, z):
        out = self.decode(params, z)
        if self.reconstruction == "bernoulli":
            # stable log-prob from logits
            return -jnp.sum(jnp.maximum(out, 0) - out * x + jnp.log1p(jnp.exp(-jnp.abs(out))), axis=-1)
        mu, log_var = jnp.split(out, 2, axis=-1)
        return -jnp.sum(
            _HALF_LOG_2PI + 0.5 * log_var + 0.5 * (x - mu) ** 2 / jnp.exp(log_var), axis=-1
        )

    def elbo_loss(self, params, x, rng) -> jax.Array:
        """Negative ELBO averaged over the batch (the layer's pretrain score;
        reference computeGradientAndScore in the VAE impl)."""
        mean, log_var = self.encode(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(log_var) + mean**2 - 1.0 - log_var, axis=-1)
        rec = 0.0
        keys = jax.random.split(rng, self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            rec = rec + self._reconstruction_log_prob(params, x, z)
        rec = rec / self.num_samples
        return jnp.mean(kl - rec)

    def reconstruction_log_probability(self, params, x, rng, num_samples: int = 5):
        """Importance-sampled log p(x) estimate
        (reconstructionLogProbability in the reference)."""
        mean, log_var = self.encode(params, x)
        lse_terms = []
        for k in jax.random.split(rng, num_samples):
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            log_px_z = self._reconstruction_log_prob(params, x, z)
            log_pz = -jnp.sum(_HALF_LOG_2PI + 0.5 * z**2, axis=-1)
            log_qz = -jnp.sum(
                _HALF_LOG_2PI + 0.5 * log_var + 0.5 * eps**2, axis=-1
            )
            lse_terms.append(log_px_z + log_pz - log_qz)
        stack = jnp.stack(lse_terms)
        return jax.scipy.special.logsumexp(stack, axis=0) - jnp.log(num_samples)

    def generate(self, params, z):
        """Decode latent codes to reconstruction means (generateAtMeanGivenZ)."""
        out = self.decode(params, z)
        if self.reconstruction == "bernoulli":
            return jax.nn.sigmoid(out)
        mu, _ = jnp.split(out, 2, axis=-1)
        return mu
