"""Global pooling (GlobalPoolingLayer.java): reduce over time ([b,t,f]->[b,f])
or spatial dims ([b,h,w,c]->[b,c]); MAX | AVG | SUM | PNORM; mask-aware for
time-series input like the reference's masked pooling
(util/MaskedReductionUtil.java)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from deeplearning4j_tpu.nn.config import LayerConfig, register_layer
from deeplearning4j_tpu.nn.input_type import InputType


@register_layer("global_pooling")
@dataclass
class GlobalPooling(LayerConfig):
    pooling: str = "max"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "conv":
            return InputType.feed_forward(input_type.channels)
        return input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim == 3:  # [b, t, f], reduce over time with mask
            axes = (1,)
            if mask is not None:
                m = mask[..., None].astype(x.dtype)
                if self.pooling == "max":
                    neg = jnp.asarray(-jnp.inf, x.dtype)
                    y = jnp.max(jnp.where(m > 0, x, neg), axis=1)
                elif self.pooling in ("avg", "mean"):
                    y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
                elif self.pooling == "sum":
                    y = jnp.sum(x * m, axis=1)
                elif self.pooling == "pnorm":
                    p = float(self.pnorm)
                    y = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
                else:
                    raise ValueError(self.pooling)
                return y, state
        elif x.ndim == 4:  # [b, h, w, c]
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPooling expects rank 3 or 4 input, got {x.shape}")

        if self.pooling == "max":
            y = jnp.max(x, axis=axes)
        elif self.pooling in ("avg", "mean"):
            y = jnp.mean(x, axis=axes)
        elif self.pooling == "sum":
            y = jnp.sum(x, axis=axes)
        elif self.pooling == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(self.pooling)
        return y, state

    def propagate_mask(self, mask, input_type):
        return None
