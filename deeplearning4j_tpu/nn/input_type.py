"""Input types: shape metadata flowing between layers at config time.

Parity with the reference's ``InputType``
(/root/reference/deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/inputs/InputType.java:117,140,176)
which drives nIn inference and automatic preprocessor insertion.

TPU-first convention change: convolutional activations are **NHWC**
([batch, height, width, channels]) and recurrent activations are
**[batch, time, features]** — the layouts XLA:TPU tiles best — whereas the
reference uses NCHW and [batch, features, time]. The config surface is
unchanged; only the runtime layout differs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class InputType:
    """Tagged union: kind in {"ff", "recurrent", "conv", "conv_flat"}."""

    kind: str
    size: int = 0                      # ff / recurrent feature size
    timesteps: Optional[int] = None    # recurrent (None = variable)
    height: int = 0
    width: int = 0
    channels: int = 0

    # -- constructors (mirror InputType.feedForward/recurrent/convolutional) --
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="recurrent", size=int(size), timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="conv", height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        """Flattened image input (e.g. MNIST rows of 784), like
        InputType.convolutionalFlat — triggers a reshape preprocessor."""
        return InputType(
            kind="conv_flat",
            size=int(height * width * channels),
            height=int(height),
            width=int(width),
            channels=int(channels),
        )

    # -- derived ----------------------------------------------------------
    def flat_size(self) -> int:
        if self.kind in ("ff", "conv_flat"):
            return self.size
        if self.kind == "recurrent":
            return self.size
        if self.kind == "conv":
            return self.height * self.width * self.channels
        raise ValueError(self.kind)

    def batch_shape(self, batch: int = 1) -> Tuple[int, ...]:
        """Concrete array shape for a batch of this input type (NHWC / BTF)."""
        if self.kind in ("ff", "conv_flat"):
            return (batch, self.size)
        if self.kind == "recurrent":
            t = self.timesteps if self.timesteps is not None else 1
            return (batch, t, self.size)
        if self.kind == "conv":
            return (batch, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    # -- serde ------------------------------------------------------------
    def to_dict(self) -> dict:
        d = {"kind": self.kind}
        if self.kind in ("ff", "conv_flat", "recurrent"):
            d["size"] = self.size
        if self.kind == "recurrent":
            d["timesteps"] = self.timesteps
        if self.kind in ("conv", "conv_flat"):
            d.update(height=self.height, width=self.width, channels=self.channels)
        return d

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        kind = d["kind"]
        if kind == "ff":
            return InputType.feed_forward(d["size"])
        if kind == "recurrent":
            return InputType.recurrent(d["size"], d.get("timesteps"))
        if kind == "conv":
            return InputType.convolutional(d["height"], d["width"], d["channels"])
        if kind == "conv_flat":
            return InputType.convolutional_flat(d["height"], d["width"], d["channels"])
        raise ValueError(f"Unknown InputType kind '{kind}'")
