"""Observability stack (deeplearning4j-ui-parent parity).

Reference chain: StatsListener (ui-model/.../stats/BaseStatsListener.java:43,
iterationDone:304 — score, per-param histograms/means/stdev of
weights/updates, memory, timing) -> StatsStorageRouter -> StatsStorage impls
(InMemory/File, ui/storage/) -> PlayUIServer train modules
(/train/overview, /train/model, /train/system).

TPU-first redesign: stats are plain JSON records (no SBE/Agrona binary
encoding — that existed for JVM off-heap buffers); the dashboard is ONE
self-contained static HTML file with inline SVG charts (no Play server, no
external JS, works air-gapped), plus the same attach() surface so training
jobs stream into storage and the page re-renders on demand.
"""

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    ChartTimeline,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    render_html,
    save_html,
)
from deeplearning4j_tpu.ui.convolutional import ConvolutionalIterationListener
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteStatsStorageRouter,
    StatsStorage,
)
from deeplearning4j_tpu.ui.server import UIServer

__all__ = [
    "StatsListener",
    "ConvolutionalIterationListener",
    "StatsStorage",
    "InMemoryStatsStorage",
    "FileStatsStorage",
    "RemoteStatsStorageRouter",
    "UIServer",
    "Component",
    "ChartLine",
    "ChartScatter",
    "ChartHistogram",
    "ChartHorizontalBar",
    "ChartStackedArea",
    "ChartTimeline",
    "ComponentText",
    "ComponentTable",
    "ComponentDiv",
    "render_html",
    "save_html",
]
