"""Stats storage: the record store the UI reads from.

Reference surface: deeplearning4j-core api/storage/StatsStorage.java +
StatsStorageRouter.java (putUpdate/putStaticInfo, listSessionIDs,
getAllUpdatesAfter, listeners) and the ui/storage impls
(InMemoryStatsStorage, FileStatsStorage). Records here are plain dicts
with (session_id, type_id, worker_id, timestamp) keys; FileStatsStorage
appends JSON lines so a crashed run's stats survive.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional


class StatsStorage:
    """Router + query API (StatsStorageRouter / StatsStorage)."""

    def __init__(self):
        self._static: List[dict] = []
        self._updates: List[dict] = []
        self._listeners: List[Callable[[dict], None]] = []
        self._lock = threading.Lock()

    # -- router side -------------------------------------------------------
    def put_static_info(self, record: dict) -> None:
        record = dict(record, kind="static", timestamp=record.get("timestamp", time.time()))
        with self._lock:
            self._static.append(record)
        self._notify(record)

    def put_update(self, record: dict) -> None:
        record = dict(record, kind="update", timestamp=record.get("timestamp", time.time()))
        with self._lock:
            self._updates.append(record)
        self._notify(record)

    def _notify(self, record: dict) -> None:
        for cb in list(self._listeners):
            cb(record)

    def register_listener(self, cb: Callable[[dict], None]) -> None:
        self._listeners.append(cb)

    # -- query side --------------------------------------------------------
    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted({r["session_id"] for r in self._static + self._updates})

    def list_worker_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({
                r.get("worker_id", "0") for r in self._updates
                if r["session_id"] == session_id
            })

    def get_static_info(self, session_id: str) -> List[dict]:
        with self._lock:
            return [r for r in self._static if r["session_id"] == session_id]

    def get_all_updates(self, session_id: str) -> List[dict]:
        with self._lock:
            return [r for r in self._updates if r["session_id"] == session_id]

    def get_all_updates_after(self, session_id: str, timestamp: float) -> List[dict]:
        return [r for r in self.get_all_updates(session_id) if r["timestamp"] > timestamp]

    def get_latest_update(self, session_id: str) -> Optional[dict]:
        ups = self.get_all_updates(session_id)
        return ups[-1] if ups else None


InMemoryStatsStorage = StatsStorage


class FileStatsStorage(StatsStorage):
    """Durable JSON-lines storage (ui/storage FileStatsStorage capability):
    every record appends to ``path``; existing records load on open."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    r = json.loads(line)
                    (self._static if r.get("kind") == "static" else self._updates).append(r)
        self._file = open(path, "a")

    def _append(self, record: dict) -> None:
        self._file.write(json.dumps(record, default=float) + "\n")
        self._file.flush()

    def put_static_info(self, record: dict) -> None:
        record = dict(record, kind="static", timestamp=record.get("timestamp", time.time()))
        with self._lock:
            self._static.append(record)
            self._append(record)
        self._notify(record)

    def put_update(self, record: dict) -> None:
        record = dict(record, kind="update", timestamp=record.get("timestamp", time.time()))
        with self._lock:
            self._updates.append(record)
            self._append(record)
        self._notify(record)

    def close(self) -> None:
        self._file.close()


class RemoteStatsStorageRouter(StatsStorage):
    """Posts records to a remote UIServer's ``/remote`` endpoint
    (ui-model/.../impl/RemoteUIStatsStorageRouter.java capability): a
    training process streams stats into a dashboard served elsewhere.
    Implements the StatsStorage *write* surface; reads happen server-side.

    Fire-and-forget for real: ``put_*`` only appends to a bounded buffer;
    a daemon worker thread drains it over HTTP, so the training loop never
    waits on a socket (a blackholed UI host would otherwise stall every
    iteration for the full timeout). ``flush()`` blocks until the buffer
    drains — for shutdown or tests."""

    def __init__(self, url: str, timeout: float = 2.0, max_buffer: int = 4096):
        super().__init__()
        self.url = url.rstrip("/") + "/remote"
        self.timeout = timeout
        self.max_buffer = max_buffer
        self._pending: List[dict] = []
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self._worker = threading.Thread(target=self._drain_loop, daemon=True)
        self._worker.start()

    @staticmethod
    def _coerce(o):
        """JSON fallback: numpy scalars/arrays and anything else become
        plain numbers/lists/strings — a stats record must never raise out
        of the training loop."""
        if hasattr(o, "tolist"):
            return o.tolist()
        try:
            return float(o)
        except (TypeError, ValueError):
            return str(o)

    def _post(self, records: List[dict]) -> bool:
        import urllib.error
        import urllib.request

        try:
            data = json.dumps(records, default=self._coerce).encode("utf-8")
        except (TypeError, ValueError):
            return True  # unserializable despite coercion: drop, don't
            # retry forever — re-posting can never succeed
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status == 200
        except urllib.error.HTTPError as e:
            # 4xx = the server REJECTED the batch (e.g. missing session_id):
            # retrying can never succeed — drop it like unserializable
            # records. 5xx/other statuses stay retryable.
            return 400 <= e.code < 500
        except Exception:
            # network errors AND protocol surprises (BadStatusLine,
            # IncompleteRead, ... are not OSError): the drain worker must
            # survive anything — telemetry never takes the process down
            return False

    def _drain_loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._pending:
                        self._idle.set()
                        break
                    self._idle.clear()
                    batch, self._pending = self._pending, []
                if not self._post(batch):
                    with self._lock:
                        # keep for retry, bounded; back off until next wake
                        self._pending = (batch + self._pending)[-self.max_buffer:]
                    break

    def _send(self, record: dict) -> None:
        with self._lock:
            self._pending.append(record)
            del self._pending[:-self.max_buffer]
            self._idle.clear()
        self._wake.set()

    def put_static_info(self, record: dict) -> None:
        self._send(dict(record, _kind="static",
                        timestamp=record.get("timestamp", time.time())))

    def put_update(self, record: dict) -> None:
        self._send(dict(record, _kind="update",
                        timestamp=record.get("timestamp", time.time())))

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until the buffer drains (or timeout); True if drained.
        Deadline is monotonic — wall-clock jumps (NTP, DST) must not hang
        or cut short the wait."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._wake.set()
            if self._idle.wait(timeout=0.05) and self.pending_count() == 0:
                return True
        # _idle guard: an in-flight batch (buffer empty, worker mid-POST)
        # must not report as drained
        return self._idle.is_set() and self.pending_count() == 0

    def close(self) -> None:
        self._stop = True
        self._wake.set()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)
