"""Training-UI internationalization — DefaultI18N parity.

Reference: deeplearning4j-ui-parent/deeplearning4j-play/src/main/java/org/
deeplearning4j/ui/i18n/DefaultI18N.java — per-language key->message maps
loaded from "somekey.langcode" resource files, with fallback to English
for keys a language lacks, a process-wide instance, and
setDefaultLanguage().

Here the common train-UI messages ship embedded for the languages the
reference localizes most fully (en, ja, zh, ko, de, fr, ru); additional
languages or keys load from resource files in the reference's own format
(``load_directory``: files named ``<anything>.<langcode>`` holding
``key=value`` lines, '#' comments). Unknown key -> the key itself,
unknown language -> English — both DefaultI18N behaviors.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

DEFAULT_LANGUAGE = "en"
FALLBACK_LANGUAGE = "en"

_MESSAGES: Dict[str, Dict[str, str]] = {
    "en": {
        "train.system": "System",
        "train.pagetitle": "deeplearning4j_tpu training UI",
        "train.overview.title": "Training overview",
        "train.session": "Session",
        "train.overview.chart.score": "Score vs iteration",
        "train.overview.chart.throughput": "Throughput (samples/sec)",
        "train.model.chart.l2norm": "Parameter L2 norms",
        "train.model.chart.updateratio": "Update/parameter ratio (learning-rate health)",
        "train.model.histograms": "Weight histograms (latest iteration)",
        "tsne.title": "t-SNE embeddings",
        "tsne.points": "points",
        "tsne.empty": ("No embeddings uploaded — POST JSON "
                       "{\"coords\": [[x,y]...], \"labels\": [...]} to "
                       "/tsne, or call UIServer.upload_tsne()."),
    },
    "ja": {
        "train.system": "システム",
        "train.pagetitle": "deeplearning4j_tpu トレーニングUI",
        "train.overview.title": "トレーニング概要",
        "train.session": "セッション",
        "train.overview.chart.score": "スコア対反復回数",
        "train.overview.chart.throughput": "スループット (サンプル/秒)",
        "train.model.chart.l2norm": "パラメータL2ノルム",
        "train.model.chart.updateratio": "更新/パラメータ比率 (学習率の健全性)",
        "train.model.histograms": "重みヒストグラム (最新の反復)",
        "tsne.title": "t-SNE埋め込み",
        "tsne.points": "点",
    },
    "zh": {
        "train.system": "系统",
        "train.pagetitle": "deeplearning4j_tpu 训练界面",
        "train.overview.title": "训练概览",
        "train.session": "会话",
        "train.overview.chart.score": "得分与迭代次数",
        "train.overview.chart.throughput": "吞吐量 (样本/秒)",
        "train.model.chart.l2norm": "参数L2范数",
        "train.model.chart.updateratio": "更新/参数比率 (学习率健康度)",
        "train.model.histograms": "权重直方图 (最新迭代)",
        "tsne.title": "t-SNE嵌入",
        "tsne.points": "个点",
    },
    "ko": {
        "train.system": "시스템",
        "train.pagetitle": "deeplearning4j_tpu 훈련 UI",
        "train.overview.title": "훈련 개요",
        "train.session": "세션",
        "train.overview.chart.score": "점수 대 반복",
        "train.overview.chart.throughput": "처리량 (샘플/초)",
        "train.model.chart.l2norm": "파라미터 L2 노름",
        "train.model.chart.updateratio": "업데이트/파라미터 비율 (학습률 상태)",
        "train.model.histograms": "가중치 히스토그램 (최근 반복)",
        "tsne.title": "t-SNE 임베딩",
        "tsne.points": "포인트",
    },
    "de": {
        "train.system": "System",
        "train.pagetitle": "deeplearning4j_tpu Trainings-UI",
        "train.overview.title": "Trainingsübersicht",
        "train.session": "Sitzung",
        "train.overview.chart.score": "Score über Iterationen",
        "train.overview.chart.throughput": "Durchsatz (Beispiele/Sek.)",
        "train.model.chart.l2norm": "Parameter-L2-Normen",
        "train.model.chart.updateratio": "Update/Parameter-Verhältnis (Lernraten-Gesundheit)",
        "train.model.histograms": "Gewichtshistogramme (letzte Iteration)",
        "tsne.title": "t-SNE-Einbettungen",
        "tsne.points": "Punkte",
    },
    "fr": {
        "train.system": "Système",
        "train.pagetitle": "Interface d'entraînement deeplearning4j_tpu",
        "train.overview.title": "Vue d'ensemble de l'entraînement",
        "train.session": "Session",
        "train.overview.chart.score": "Score par itération",
        "train.overview.chart.throughput": "Débit (échantillons/s)",
        "train.model.chart.l2norm": "Normes L2 des paramètres",
        "train.model.chart.updateratio": "Ratio mise à jour/paramètre (santé du taux d'apprentissage)",
        "train.model.histograms": "Histogrammes des poids (dernière itération)",
        "tsne.title": "Plongements t-SNE",
        "tsne.points": "points",
    },
    "ru": {
        "train.system": "Система",
        "train.pagetitle": "deeplearning4j_tpu — интерфейс обучения",
        "train.overview.title": "Обзор обучения",
        "train.session": "Сессия",
        "train.overview.chart.score": "Оценка по итерациям",
        "train.overview.chart.throughput": "Пропускная способность (образцов/с)",
        "train.model.chart.l2norm": "L2-нормы параметров",
        "train.model.chart.updateratio": "Отношение обновление/параметр (здоровье шага обучения)",
        "train.model.histograms": "Гистограммы весов (последняя итерация)",
        "tsne.title": "t-SNE-вложения",
        "tsne.points": "точек",
    },
}


class I18N:
    """Per-process message provider (DefaultI18N.getInstance surface)."""

    _instance: Optional["I18N"] = None

    def __init__(self):
        self._messages: Dict[str, Dict[str, str]] = {
            lang: dict(tbl) for lang, tbl in _MESSAGES.items()
        }
        self._default = DEFAULT_LANGUAGE

    @classmethod
    def get_instance(cls) -> "I18N":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    # -- DefaultI18N surface ----------------------------------------------
    def get_message(self, key: str, lang: Optional[str] = None) -> str:
        """Message for ``key`` in ``lang`` (default language when None),
        falling back to English, then to the key itself."""
        lang = (lang or self._default).lower()
        for table in (self._messages.get(lang),
                      self._messages.get(FALLBACK_LANGUAGE)):
            if table and key in table:
                return table[key]
        return key

    def get_default_language(self) -> str:
        return self._default

    def set_default_language(self, lang: str) -> "I18N":
        self._default = lang.lower()
        return self

    def languages(self):
        return sorted(self._messages)

    # -- resource files (the reference's "somekey.langcode" format) -------
    def load_file(self, path: str) -> "I18N":
        """One resource file named ``<anything>.<langcode>`` holding
        ``key=value`` lines ('#'/'!' comments, blank lines ignored)."""
        name = os.path.basename(path)
        lang = name.rsplit(".", 1)[-1].lower() if "." in name else ""
        if not (2 <= len(lang) <= 3 and lang.isalpha()):
            raise ValueError(
                f"resource file {name!r} needs a language-code extension "
                "(e.g. messages.en)")
        table = self._messages.setdefault(lang, {})
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line[0] in "#!" or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                table[k.strip()] = v.strip()
        return self

    def load_directory(self, path: str) -> "I18N":
        """Load every resource file of a dl4j_i18n-style directory. Only
        files whose extension LOOKS like a language code (2-3 lowercase
        letters) register — a stray README.md would otherwise pollute
        languages() with a bogus 'md' pack."""
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            ext = name.rsplit(".", 1)[-1] if "." in name else ""
            if os.path.isfile(full) and 2 <= len(ext) <= 3 \
                    and ext.isalpha() and ext.islower():
                self.load_file(full)
        return self
