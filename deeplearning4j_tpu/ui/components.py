"""Standalone chart/component DSL + static page renderer.

Capability parity with the reference's `deeplearning4j-ui-components`
module (components/chart/ChartLine.java:37, ChartScatter.java:36,
ChartHistogram.java:36, ChartHorizontalBar.java:31, ChartStackedArea.java:38,
ChartTimeline.java:26, text/ComponentText.java, table/ComponentTable.java,
component/ComponentDiv.java, standalone/StaticPageUtil.java:40-110).

Reference components serialize to JSON and render through FreeMarker +
d3.js templates; here each component serializes to the same
``{"componentType": ..., fields...}`` shape and renders to self-contained
inline SVG/HTML (air-gap friendly, no JS) — the same design the dashboard
(`ui/server.py`) uses. ``render_html``/``save_html`` mirror
StaticPageUtil.renderHTML/saveHTMLFile.
"""

from __future__ import annotations

import html as _html
import json
from typing import Dict, List, Optional, Sequence, Type

_PALETTE = ["#1976d2", "#e53935", "#43a047", "#fb8c00", "#8e24aa",
            "#00897b", "#6d4c41", "#3949ab"]

_REGISTRY: Dict[str, Type["Component"]] = {}


class Component:
    """Base: every component has a ``component_type``, JSON serde, and an
    HTML fragment renderer."""

    component_type = "Component"

    def to_dict(self) -> dict:
        d = {"componentType": self.component_type}
        d.update({k: v for k, v in self.__dict__.items() if v is not None})
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "Component":
        t = d.get("componentType")
        cls = _REGISTRY.get(t)
        if cls is None:
            raise ValueError(f"Unknown componentType {t!r}")
        obj = cls.__new__(cls)
        obj.__dict__.update({k: v for k, v in d.items() if k != "componentType"})
        return obj

    @staticmethod
    def from_json(s: str) -> "Component":
        return Component.from_dict(json.loads(s))

    def render(self) -> str:  # pragma: no cover - abstract
        raise NotImplementedError

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        # abstract intermediates (e.g. _SeriesChart) define no
        # component_type of their own — keep them out of the serde registry
        if "component_type" in cls.__dict__:
            _REGISTRY[cls.component_type] = cls

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.to_dict() == other.to_dict())

    def __hash__(self):
        # value hash over the serialized state: equal components hash equal.
        # Caveat: components are mutable builders — finish building (all
        # add_series/add_bin calls) BEFORE using one as a set/dict key.
        return hash(self.to_json())

    def __repr__(self):
        return f"{type(self).__name__}({self.to_dict()})"


def _axes(xs, ys, w, h, pad):
    x0, x1 = (min(xs), max(xs)) if len(xs) else (0.0, 1.0)
    y0, y1 = (min(ys), max(ys)) if len(ys) else (0.0, 1.0)
    sx = lambda x: pad + (x - x0) / ((x1 - x0) or 1.0) * (w - 2 * pad)
    sy = lambda y: h - pad - (y - y0) / ((y1 - y0) or 1.0) * (h - 2 * pad)
    labels = (
        f'<text x="{pad}" y="{h - 6}" class="ax">{x0:.4g}</text>'
        f'<text x="{w - pad}" y="{h - 6}" class="ax" text-anchor="end">{x1:.4g}</text>'
        f'<text x="4" y="{h - pad}" class="ax">{y0:.4g}</text>'
        f'<text x="4" y="{pad}" class="ax">{y1:.4g}</text>')
    return sx, sy, labels


def _svg(title: str, w: int, h: int, body: str, legend: str = "") -> str:
    return (
        f'<div class="card"><h3>{_html.escape(title or "")}</h3>'
        f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}">'
        f'<rect width="{w}" height="{h}" fill="#fafafa" stroke="#ddd"/>'
        f"{body}"
        + (f'<text x="40" y="14" class="ax">{legend}</text>' if legend else "")
        + "</svg></div>")


class _SeriesChart(Component):
    """Shared builder surface for multi-series x/y charts
    (Chart.Builder.addSeries in the reference)."""

    def __init__(self, title: str = ""):
        self.title = title
        self.x: List[List[float]] = []
        self.y: List[List[float]] = []
        self.seriesNames: List[str] = []

    def add_series(self, name: str, x_values: Sequence[float],
                   y_values: Sequence[float]) -> "_SeriesChart":
        if len(x_values) != len(y_values):
            raise ValueError(
                f"series {name!r}: {len(x_values)} x vs {len(y_values)} y values")
        self.x.append([float(v) for v in x_values])
        self.y.append([float(v) for v in y_values])
        self.seriesNames.append(name)
        return self

    def _legend(self) -> str:
        return "".join(
            f'<tspan fill="{_PALETTE[i % len(_PALETTE)]}">&#9632; '
            f"{_html.escape(n)}</tspan> "
            for i, n in enumerate(self.seriesNames))


class ChartLine(_SeriesChart):
    component_type = "ChartLine"

    def render(self, w: int = 640, h: int = 220, pad: int = 42) -> str:
        all_x = [v for s in self.x for v in s]
        all_y = [v for s in self.y for v in s]
        sx, sy, labels = _axes(all_x, all_y, w, h, pad)
        body = []
        for i, (xs, ys) in enumerate(zip(self.x, self.y)):
            pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
            body.append(f'<polyline fill="none" stroke="{_PALETTE[i % len(_PALETTE)]}" '
                        f'stroke-width="1.6" points="{pts}"/>')
        return _svg(self.title, w, h, "".join(body) + labels, self._legend())


class ChartScatter(_SeriesChart):
    component_type = "ChartScatter"

    def render(self, w: int = 640, h: int = 220, pad: int = 42) -> str:
        all_x = [v for s in self.x for v in s]
        all_y = [v for s in self.y for v in s]
        sx, sy, labels = _axes(all_x, all_y, w, h, pad)
        body = []
        for i, (xs, ys) in enumerate(zip(self.x, self.y)):
            c = _PALETTE[i % len(_PALETTE)]
            body.extend(f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="2.4" '
                        f'fill="{c}"/>' for x, y in zip(xs, ys))
        return _svg(self.title, w, h, "".join(body) + labels, self._legend())


class ChartStackedArea(_SeriesChart):
    component_type = "ChartStackedArea"

    def add_series(self, name, x_values, y_values):
        # stacking requires one shared x grid across all series
        if self.x and list(x_values) != list(self.x[0]):
            raise ValueError(
                f"stacked series {name!r} must share the first series' x "
                f"grid ({len(self.x[0])} points)")
        return super().add_series(name, x_values, y_values)

    def render(self, w: int = 640, h: int = 220, pad: int = 42) -> str:
        if not self.x:
            return _svg(self.title, w, h, "")
        xs = self.x[0]
        cum = [0.0] * len(xs)
        stacks = []
        for ys in self.y:
            cum = [a + b for a, b in zip(cum, ys)]
            stacks.append(list(cum))
        sx, sy, labels = _axes(xs, [0.0] + stacks[-1], w, h, pad)
        body = []
        prev = [0.0] * len(xs)
        for i, top in enumerate(stacks):
            up = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, top))
            dn = " ".join(f"{sx(x):.1f},{sy(y):.1f}"
                          for x, y in reversed(list(zip(xs, prev))))
            body.append(f'<polygon fill="{_PALETTE[i % len(_PALETTE)]}" '
                        f'fill-opacity="0.65" points="{up} {dn}"/>')
            prev = top
        return _svg(self.title, w, h, "".join(body) + labels, self._legend())


class ChartTimeline(Component):
    """Lanes of [start, end, label] entries (ChartTimeline.java)."""

    component_type = "ChartTimeline"

    def __init__(self, title: str = ""):
        self.title = title
        self.laneNames: List[str] = []
        self.laneData: List[List[dict]] = []

    def add_lane(self, name: str, entries: Sequence[dict]) -> "ChartTimeline":
        """entries: [{"start": t0, "end": t1, "label": ...}, ...]"""
        self.laneNames.append(name)
        self.laneData.append([dict(e) for e in entries])
        return self

    def render(self, w: int = 640, h: Optional[int] = None, pad: int = 42) -> str:
        lanes = len(self.laneData) or 1
        h = h or (40 + 26 * lanes)
        ts = [e[k] for lane in self.laneData for e in lane for k in ("start", "end")]
        t0, t1 = (min(ts), max(ts)) if ts else (0.0, 1.0)
        sx = lambda t: pad + (t - t0) / ((t1 - t0) or 1.0) * (w - 2 * pad)
        body = []
        for li, lane in enumerate(self.laneData):
            y = 24 + 26 * li
            body.append(f'<text x="4" y="{y + 13}" class="ax">'
                        f"{_html.escape(self.laneNames[li])}</text>")
            for ei, e in enumerate(lane):
                x0, x1 = sx(e["start"]), sx(e["end"])
                c = _PALETTE[ei % len(_PALETTE)]
                body.append(f'<rect x="{x0:.1f}" y="{y}" width="{max(x1 - x0, 1):.1f}" '
                            f'height="18" fill="{c}" fill-opacity="0.8"/>')
                if e.get("label"):
                    body.append(f'<text x="{x0 + 2:.1f}" y="{y + 13}" class="ax">'
                                f'{_html.escape(str(e["label"]))}</text>')
        return _svg(self.title, w, h, "".join(body))


class ChartHistogram(Component):
    """Explicit-bin histogram: add_bin(lower, upper, y) (ChartHistogram.java)."""

    component_type = "ChartHistogram"

    def __init__(self, title: str = ""):
        self.title = title
        self.lowerBounds: List[float] = []
        self.upperBounds: List[float] = []
        self.yValues: List[float] = []

    def add_bin(self, lower: float, upper: float, y: float) -> "ChartHistogram":
        self.lowerBounds.append(float(lower))
        self.upperBounds.append(float(upper))
        self.yValues.append(float(y))
        return self

    def render(self, w: int = 640, h: int = 220, pad: int = 42) -> str:
        if not self.yValues:
            return _svg(self.title, w, h, "")
        x0, x1 = min(self.lowerBounds), max(self.upperBounds)
        ymax = max(self.yValues) or 1.0
        sx = lambda x: pad + (x - x0) / ((x1 - x0) or 1.0) * (w - 2 * pad)
        body = []
        for lo, hi, y in zip(self.lowerBounds, self.upperBounds, self.yValues):
            bh = (h - 2 * pad) * y / ymax
            body.append(f'<rect x="{sx(lo):.1f}" y="{h - pad - bh:.1f}" '
                        f'width="{max(sx(hi) - sx(lo) - 1, 1):.1f}" '
                        f'height="{bh:.1f}" fill="#1976d2"/>')
        labels = (f'<text x="{pad}" y="{h - 6}" class="ax">{x0:.4g}</text>'
                  f'<text x="{w - pad}" y="{h - 6}" class="ax" '
                  f'text-anchor="end">{x1:.4g}</text>')
        return _svg(self.title, w, h, "".join(body) + labels)


class ChartHorizontalBar(Component):
    component_type = "ChartHorizontalBar"

    def __init__(self, title: str = ""):
        self.title = title
        self.labels: List[str] = []
        self.values: List[float] = []

    def add_value(self, label: str, value: float) -> "ChartHorizontalBar":
        self.labels.append(label)
        self.values.append(float(value))
        return self

    def render(self, w: int = 640, h: Optional[int] = None, pad: int = 90) -> str:
        n = len(self.values) or 1
        h = h or (30 + 24 * n)
        vmax = max([abs(v) for v in self.values] or [1.0]) or 1.0
        body = []
        for i, (lab, v) in enumerate(zip(self.labels, self.values)):
            y = 18 + 24 * i
            bw = (w - pad - 20) * abs(v) / vmax
            body.append(f'<text x="4" y="{y + 12}" class="ax">'
                        f"{_html.escape(lab)}</text>")
            body.append(f'<rect x="{pad}" y="{y}" width="{bw:.1f}" height="16" '
                        f'fill="{_PALETTE[i % len(_PALETTE)]}"/>')
            body.append(f'<text x="{pad + bw + 4:.1f}" y="{y + 12}" class="ax">'
                        f"{v:.4g}</text>")
        return _svg(self.title, w, h, "".join(body))


class ComponentText(Component):
    component_type = "ComponentText"

    def __init__(self, text: str = ""):
        self.text = text

    def render(self) -> str:
        return f"<p>{_html.escape(self.text)}</p>"


class ComponentTable(Component):
    component_type = "ComponentTable"

    def __init__(self, header: Optional[Sequence[str]] = None,
                 content: Optional[Sequence[Sequence[str]]] = None):
        self.header = list(header) if header else []
        self.content = [list(r) for r in content] if content else []

    def render(self) -> str:
        head = "".join(f"<th>{_html.escape(str(c))}</th>" for c in self.header)
        rows = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in r) + "</tr>"
            for r in self.content)
        return f"<table><tr>{head}</tr>{rows}</table>"


class ComponentDiv(Component):
    """Container grouping child components (ComponentDiv.java)."""

    component_type = "ComponentDiv"

    def __init__(self, *children: Component):
        self.components = [c.to_dict() for c in children]

    def children(self) -> List[Component]:
        return [Component.from_dict(d) for d in self.components]

    def render(self) -> str:
        return ("<div>" + "".join(c.render() for c in self.children())
                + "</div>")


_CSS = """
body { font-family: system-ui, sans-serif; margin: 20px; color: #222; }
h3 { font-size: 13px; margin: 6px 0; }
.card { display: inline-block; margin: 8px; vertical-align: top; }
.ax { font-size: 9px; fill: #666; }
table { border-collapse: collapse; font-size: 12px; margin: 8px; }
td, th { border: 1px solid #ccc; padding: 3px 8px; }
p { max-width: 640px; }
"""


def render_html(*components: Component, title: str = "deeplearning4j_tpu") -> str:
    """StaticPageUtil.renderHTML parity: one self-contained HTML page."""
    if len(components) == 1 and isinstance(components[0], (list, tuple)):
        components = tuple(components[0])
    body = "\n".join(c.render() for c in components)
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(title)}</title><style>{_CSS}</style></head>"
            f"<body>{body}</body></html>")


def save_html(path: str, *components: Component,
              title: str = "deeplearning4j_tpu") -> None:
    """StaticPageUtil.saveHTMLFile parity."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(render_html(*components, title=title))


def reliability_chart(calibration, cls: int = 0) -> ChartLine:
    """Reliability diagram as a ChartLine (the reference UI's calibration
    page capability, rendered through this module's DSL): predicted
    probability vs observed frequency for one class, plus the y=x ideal."""
    mean_pred, frac_pos = calibration.reliability_diagram(cls)
    counts = calibration.rel_count[cls]
    chart = ChartLine(f"Reliability (class {cls})")
    chart.add_series("ideal", [0.0, 1.0], [0.0, 1.0])
    # empty bins report (0, 0) — plotting them would zigzag the polyline
    # back to the origin mid-curve
    chart.add_series("observed",
                     [float(p) for p, c in zip(mean_pred, counts) if c > 0],
                     [float(f) for f, c in zip(frac_pos, counts) if c > 0])
    return chart
