"""StatsListener: the producer side of the observability chain.

Reference: BaseStatsListener.java:43 (iterationDone:304 collects score,
per-parameter histograms/means/stdev of weights and updates, memory,
timing; gc stats at :389). Here the same signals come off the pytree:
per-layer/per-tensor mean, stdev, L2 norm, histogram of weights and of the
step's parameter UPDATE (delta since the listener last looked — on this
runtime the update is the observable quantity; raw gradients never leave
the fused XLA step), update/parameter ratio (the reference UI's key
learning-rate-health chart), plus wall-clock timing and throughput.
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import StatsStorage


def _tensor_stats(arr: np.ndarray, bins: int) -> dict:
    flat = arr.ravel()
    hist, edges = np.histogram(flat, bins=bins)
    return {
        "mean": float(flat.mean()),
        "stdev": float(flat.std()),
        "norm2": float(np.linalg.norm(flat)),
        "min": float(flat.min()),
        "max": float(flat.max()),
        "histogram": {"counts": hist.tolist(),
                      "lo": float(edges[0]), "hi": float(edges[-1])},
    }


def _flatten_params(params) -> Dict[str, np.ndarray]:
    """Pytree -> {"0/W": array, ...} with layer-index/name paths."""
    import jax

    out: Dict[str, np.ndarray] = {}
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        out[name] = np.asarray(leaf)
    return out


class StatsListener(TrainingListener):
    """Attachable stats producer: feeds a StatsStorage every
    ``frequency`` iterations.

    ``StatsListener(storage)`` mirrors new StatsListener(statsStorage) in
    the reference; session_id groups one training run.
    """

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "0",
                 histogram_bins: int = 20, collect_histograms: bool = True):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.bins = histogram_bins
        self.collect_histograms = collect_histograms
        self._last_params: Optional[Dict[str, np.ndarray]] = None
        self._last_time: Optional[float] = None
        self._static_sent = False
        self._samples = 0

    # -- hooks -------------------------------------------------------------
    def _send_static(self, model) -> None:
        import jax

        self.storage.put_static_info({
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "type_id": "StatsInitializationReport",
            "model_class": type(model).__name__,
            "n_layers": getattr(model, "n_layers", None),
            "n_params": int(sum(
                int(np.prod(np.shape(p)))
                for p in jax.tree_util.tree_leaves(model.params)
            )),
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
        })
        self._static_sent = True

    def iteration_done(self, model, iteration, score, batch_size=0):
        self._samples += batch_size
        if not self._static_sent:
            self._send_static(model)
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        dt = (now - self._last_time) if self._last_time is not None else None
        cur = _flatten_params(model.params)

        param_stats: Dict[str, dict] = {}
        update_stats: Dict[str, dict] = {}
        ratios: Dict[str, float] = {}
        for name, arr in cur.items():
            st = _tensor_stats(arr, self.bins)
            if not self.collect_histograms:
                st.pop("histogram", None)
            param_stats[name] = st
            if self._last_params is not None and name in self._last_params:
                upd = arr - self._last_params[name]
                ust = _tensor_stats(upd, self.bins)
                if not self.collect_histograms:
                    ust.pop("histogram", None)
                update_stats[name] = ust
                pn = st["norm2"]
                ratios[name] = float(ust["norm2"] / pn) if pn > 0 else 0.0

        self.storage.put_update({
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "type_id": "StatsReport",
            "iteration": int(iteration),
            "score": float(score),
            "duration_sec": dt,
            "samples_per_sec": (self._samples / dt) if dt else None,
            "batch_size": batch_size,
            "parameters": param_stats,
            "updates": update_stats,
            "update_ratios": ratios,
        })
        # mirror the headline scalars into the obs registry so /metrics
        # serves them without a StatsStorage reader
        from deeplearning4j_tpu import obs

        obs.gauge("dl4j_training_score",
                  "Last reported training score",
                  ("session",)).set(float(score), session=self.session_id)
        obs.counter("dl4j_training_iterations_total",
                    "Iterations observed by StatsListener",
                    ("session",)).inc(session=self.session_id)
        if dt and self._samples:
            obs.gauge("dl4j_training_samples_per_second",
                      "Recent training throughput",
                      ("session",)).set(self._samples / dt,
                                        session=self.session_id)
        self._last_params = cur
        self._last_time = now
        self._samples = 0
