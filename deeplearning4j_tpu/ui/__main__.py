"""UI server CLI: ``python -m deeplearning4j_tpu.ui``.

Reference parity: deeplearning4j-ui-parent play/PlayUIServer.java:3-14 (the
standalone dashboard process with a port flag). Attaches a durable JSONL
StatsStorage written by a training run's StatsListener and serves the
dashboard.

Example::

    python -m deeplearning4j_tpu.ui --storage runs/stats.jsonl --port 9001
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.ui",
        description="Serve the training dashboard from a stats-storage file.")
    p.add_argument("--storage", required=True,
                   help="JSONL stats file written by FileStatsStorage")
    p.add_argument("--port", type=int, default=9001)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import FileStatsStorage

    ui = UIServer.get_instance()
    ui.attach(FileStatsStorage(args.storage))
    ui.serve(args.port)
    print(f"UI server on port {ui.port}", flush=True)
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        ui.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
