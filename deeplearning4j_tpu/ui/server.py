"""UIServer: dashboard rendering + attach API.

Reference surface: UIServer.getInstance().attach(statsStorage)
(deeplearning4j-play/.../api/UIServer.java:24,49) with train modules
(/train/overview score+throughput, /train/model per-param charts,
/train/system). Re-designed: render() emits ONE static self-contained HTML
file (inline SVG, no JS dependencies, air-gap friendly); serve() optionally
exposes it plus a JSON stats endpoint over stdlib HTTP.
"""

from __future__ import annotations

import html
import json
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.ui.i18n import I18N
from deeplearning4j_tpu.ui.storage import StatsStorage


def _msg(key: str, lang=None) -> str:
    """Localized UI chrome string (ui/i18n.py, DefaultI18N parity)."""
    return I18N.get_instance().get_message(key, lang)


def _kv_table(d: dict, keys=None) -> str:
    """Escaped key/value <table> (the stats/system table renderer)."""
    rows = "".join(
        f"<tr><th>{html.escape(str(k))}</th><td>{html.escape(str(v))}</td></tr>"
        for k, v in d.items() if keys is None or k in keys)
    return f"<table>{rows}</table>"

_W, _H, _PAD = 640, 220, 42


def _polyline(xs: Sequence[float], ys: Sequence[float], color: str) -> str:
    if not xs:
        return ""
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    sx = lambda x: _PAD + (x - x0) / (x1 - x0 or 1) * (_W - 2 * _PAD)
    sy = lambda y: _H - _PAD - (y - y0) / (y1 - y0 or 1) * (_H - 2 * _PAD)
    pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
    axis_labels = (
        f'<text x="{_PAD}" y="{_H - 8}" class="ax">{x0:.4g}</text>'
        f'<text x="{_W - _PAD}" y="{_H - 8}" class="ax" text-anchor="end">{x1:.4g}</text>'
        f'<text x="4" y="{_H - _PAD}" class="ax">{y0:.4g}</text>'
        f'<text x="4" y="{_PAD}" class="ax">{y1:.4g}</text>'
    )
    return (
        f'<polyline fill="none" stroke="{color}" stroke-width="1.6" points="{pts}"/>'
        + axis_labels
    )


def _chart(title: str, series: List[Tuple[str, Sequence[float], Sequence[float]]]) -> str:
    colors = ["#1976d2", "#e53935", "#43a047", "#fb8c00", "#8e24aa",
              "#00897b", "#6d4c41", "#3949ab"]
    body, legend = [], []
    for i, (label, xs, ys) in enumerate(series):
        c = colors[i % len(colors)]
        body.append(_polyline(list(xs), list(ys), c))
        legend.append(f'<tspan fill="{c}">&#9632; {html.escape(label)}</tspan> ')
    return (
        f'<div class="card"><h3>{html.escape(title)}</h3>'
        f'<svg viewBox="0 0 {_W} {_H}" width="{_W}" height="{_H}">'
        f'<rect width="{_W}" height="{_H}" fill="#fafafa" stroke="#ddd"/>'
        + "".join(body)
        + f'<text x="{_PAD}" y="16" class="ax">{"".join(legend)}</text>'
        "</svg></div>"
    )


def _histogram_svg(title: str, counts: Sequence[int], lo: float, hi: float) -> str:
    if not counts:
        return ""
    w, h, pad = 300, 120, 24
    n = len(counts)
    mx = max(counts) or 1
    bars = []
    bw = (w - 2 * pad) / n
    for i, c in enumerate(counts):
        bh = (h - 2 * pad) * c / mx
        bars.append(
            f'<rect x="{pad + i * bw:.1f}" y="{h - pad - bh:.1f}" '
            f'width="{max(bw - 1, 1):.1f}" height="{bh:.1f}" fill="#1976d2"/>'
        )
    return (
        f'<div class="hist"><h4>{html.escape(title)}</h4>'
        f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}">'
        f'<rect width="{w}" height="{h}" fill="#fafafa" stroke="#ddd"/>'
        + "".join(bars)
        + f'<text x="{pad}" y="{h - 6}" class="ax">{lo:.3g}</text>'
        f'<text x="{w - pad}" y="{h - 6}" class="ax" text-anchor="end">{hi:.3g}</text>'
        "</svg></div>"
    )


_CSS = """
body { font-family: system-ui, sans-serif; margin: 20px; color: #222; }
h1 { font-size: 20px; } h2 { font-size: 16px; margin-top: 28px; }
h3 { font-size: 13px; margin: 6px 0; } h4 { font-size: 11px; margin: 4px 0; }
.card { display: inline-block; margin: 8px; vertical-align: top; }
.hist { display: inline-block; margin: 6px; }
.ax { font-size: 9px; fill: #666; }
table { border-collapse: collapse; font-size: 12px; }
td, th { border: 1px solid #ccc; padding: 3px 8px; }
"""


def _scatter_svg(coords, labels=None, w: int = 640, h: int = 480) -> str:
    """Inline-SVG scatter of a 2-D embedding (+ optional point labels)."""
    xs, ys = coords[:, 0], coords[:, 1]
    x0, x1 = float(xs.min()), float(xs.max())
    y0, y1 = float(ys.min()), float(ys.max())
    sx = (w - 40) / ((x1 - x0) or 1.0)
    sy = (h - 40) / ((y1 - y0) or 1.0)
    pts = []
    for i in range(len(coords)):
        px = 20 + (float(xs[i]) - x0) * sx
        py = h - 20 - (float(ys[i]) - y0) * sy
        pts.append(f"<circle cx='{px:.1f}' cy='{py:.1f}' r='3' fill='#1f77b4'/>")
        if labels is not None:
            pts.append(f"<text x='{px + 4:.1f}' y='{py - 4:.1f}' "
                       f"font-size='9'>{html.escape(labels[i])}</text>")
    return (f"<svg width='{w}' height='{h}' style='border:1px solid #ccc'>"
            + "".join(pts) + "</svg>")


class UIServer:
    """``UIServer.get_instance().attach(storage)`` then ``render(path)`` or
    ``serve(port)``."""

    _instance: Optional["UIServer"] = None

    def __init__(self):
        self.storages: List[StatsStorage] = []
        self._httpd = None
        self._thread = None
        self.port: Optional[int] = None
        self._remote_storage: Optional[StatsStorage] = None
        # /tsne embedding page (reference deeplearning4j-play
        # module/tsne/TsneModule.java): named 2-D point sets + labels
        self._tsne_sets: dict = {}

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def attach(self, storage: StatsStorage) -> "UIServer":
        if storage not in self.storages:
            self.storages.append(storage)
        return self

    def detach(self, storage: StatsStorage) -> None:
        if storage in self.storages:
            self.storages.remove(storage)

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None
                               ) -> StatsStorage:
        """Accept POSTed stats records on ``/remote`` into ``storage``
        (reference play/.../RemoteReceiverModule.java behind
        UIServer.enableRemoteListener). Records come from
        `RemoteStatsStorageRouter` in the training process."""
        if storage is None:
            from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

            storage = InMemoryStatsStorage()
        self._remote_storage = storage
        self.attach(storage)
        return storage

    # -- rendering ---------------------------------------------------------
    def render_html(self, refresh_seconds: int = 0,
                    lang: Optional[str] = None) -> str:
        """``refresh_seconds > 0`` makes the page LIVE: served pages carry a
        meta-refresh so the dashboard re-renders from storage while training
        runs (reference module/train/TrainModule.java live updates).
        ``lang`` localizes the chrome via ui/i18n.py (DefaultI18N parity;
        served pages take ``?lang=ja`` etc.)."""
        msg = lambda k: _msg(k, lang)
        refresh = (f"<meta http-equiv='refresh' content='{refresh_seconds}'>"
                   if refresh_seconds > 0 else "")
        parts = [f"<html><head><meta charset='utf-8'>{refresh}"
                 f"<style>{_CSS}</style>"
                 f"<title>{html.escape(msg('train.pagetitle'))}</title></head><body>"
                 f"<h1>{html.escape(msg('train.overview.title'))}</h1>"]
        for storage in self.storages:
            for sid in storage.list_session_ids():
                parts.append(self._render_session(storage, sid, lang))
        parts.append("</body></html>")
        return "".join(parts)

    def _render_session(self, storage: StatsStorage, sid: str,
                        lang: Optional[str] = None) -> str:
        msg = lambda k: _msg(k, lang)
        ups = [u for u in storage.get_all_updates(sid)
               if u.get("type_id") == "StatsReport"]
        statics = storage.get_static_info(sid)
        parts = [f"<h2>{html.escape(msg('train.session'))} {html.escape(sid)}</h2>"]
        if statics:
            parts.append(_kv_table(
                statics[0],
                keys=("model_class", "n_layers", "n_params", "backend",
                      "devices")))
        if not ups:
            return "".join(parts)
        its = [u["iteration"] for u in ups]
        parts.append(_chart(msg("train.overview.chart.score"),
                            [("score", its, [u["score"] for u in ups])]))
        tput = [(u["iteration"], u["samples_per_sec"]) for u in ups
                if u.get("samples_per_sec")]
        if tput:
            parts.append(_chart(msg("train.overview.chart.throughput"),
                                [("samples/sec", [t[0] for t in tput], [t[1] for t in tput])]))
        pnames = sorted(ups[-1].get("parameters", {}).keys())
        if pnames:
            parts.append(_chart(
                msg("train.model.chart.l2norm"),
                [(n, its, [u["parameters"].get(n, {}).get("norm2", 0.0) for u in ups])
                 for n in pnames],
            ))
            ratio_ups = [u for u in ups if u.get("update_ratios")]
            if ratio_ups:
                parts.append(_chart(
                    msg("train.model.chart.updateratio"),
                    [(n, [u["iteration"] for u in ratio_ups],
                      [u["update_ratios"].get(n, 0.0) for u in ratio_ups])
                     for n in pnames],
                ))
            parts.append(f"<h2>{html.escape(msg('train.model.histograms'))}</h2>")
            for n in pnames:
                hg = ups[-1]["parameters"][n].get("histogram")
                if hg:
                    parts.append(_histogram_svg(n, hg["counts"], hg["lo"], hg["hi"]))
        return "".join(parts)

    def render(self, path: str) -> str:
        """Write the dashboard to ``path``; returns the path."""
        with open(path, "w") as f:
            f.write(self.render_html())
        return path

    # -- t-SNE embedding page (TsneModule parity) --------------------------
    def upload_tsne(self, coords, labels=None, session_id: str = "tsne"):
        """Register a 2-D embedding for the ``/tsne`` page (the reference
        TsneModule's file-upload flow, as a programmatic surface — e.g.
        ``upload_tsne(BarnesHutTsne(...).fit_transform(X), words)``)."""
        import numpy as np

        coords = np.asarray(coords, float)
        if coords.ndim != 2 or coords.shape[1] < 2:
            raise ValueError(f"coords must be [n, 2+], got {coords.shape}")
        if labels is not None and len(labels) != len(coords):
            raise ValueError("labels length must match coords")
        self._tsne_sets[session_id] = (
            coords[:, :2],
            [str(l) for l in labels] if labels is not None else None,
        )
        return self

    def render_system_html(self, lang: Optional[str] = None) -> str:
        """/train/system (reference TrainModule's system tab): runtime and
        per-session hardware/memory facts — JAX backend and devices in
        place of the reference's JVM/GC telemetry, peak host RSS from the
        OS (ru_maxrss: a lifetime high-water mark, kilobytes on Linux and
        bytes on BSD/macOS)."""
        import sys as _sys

        import jax as _jax

        msg = lambda k: _msg(k, lang)
        devs = _jax.devices()
        try:
            # POSIX-only; on other hosts the page renders with RSS as n/a
            # instead of the whole endpoint 500ing
            import resource

            maxrss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            if _sys.platform == "darwin":
                maxrss //= 1024                # bytes -> KB
            peak_rss = f"{maxrss / 1024:.1f} MB"
        except (ImportError, OSError):
            peak_rss = "n/a"
        rows = {
            "backend": _jax.default_backend(),
            "devices": ", ".join(str(d) for d in devs),
            "device count": len(devs),
            "process count": _jax.process_count(),
            "peak host RSS": peak_rss,
        }
        parts = [f"<html><head><meta charset='utf-8'><style>{_CSS}</style>"
                 f"<title>{html.escape(msg('train.pagetitle'))}</title>"
                 f"</head><body><h1>{html.escape(msg('train.system'))}</h1>"
                 + _kv_table(rows)]
        for storage in self.storages:
            for sid in storage.list_session_ids():
                statics = storage.get_static_info(sid)
                if not statics:
                    continue
                parts.append(
                    f"<h2>{html.escape(msg('train.session'))} "
                    f"{html.escape(sid)}</h2>" + _kv_table(statics[0]))
        parts.append("</body></html>")
        return "".join(parts)

    def render_tsne_html(self, lang: Optional[str] = None) -> str:
        msg = lambda k: _msg(k, lang)
        title = html.escape(msg("tsne.title"))
        parts = [f"<html><head><meta charset='utf-8'><style>{_CSS}</style>"
                 f"<title>{title}</title></head><body>"
                 f"<h1>{title}</h1>"]
        if not self._tsne_sets:
            parts.append(f"<p>{html.escape(msg('tsne.empty'))}</p>")
        for sid, (coords, labels) in sorted(self._tsne_sets.items()):
            parts.append(f"<h2>{html.escape(sid)} ({len(coords)} "
                         f"{html.escape(msg('tsne.points'))})</h2>")
            parts.append(_scatter_svg(coords, labels))
        parts.append("</body></html>")
        return "".join(parts)

    # -- serving -----------------------------------------------------------
    def serve(self, port: int = 9001, warm_models=(),
              warm_batch: int = 32) -> "UIServer":
        # AOT warmup BEFORE the socket binds: a server that answers its
        # port is warm — time-to-first-request never pays an XLA compile
        # (``warm_models``: models whose inference path this server fronts;
        # ``warm_batch``: largest request batch to ladder-walk up to)
        if warm_models:
            from deeplearning4j_tpu.nn import aot

            for m in warm_models:
                aot.warm_serving(m, warm_batch)
        # SLO envelope, in-flight gauge, /metrics and /healthz all come
        # from the shared plumbing (serve/httpcommon.py) — the UI handler
        # only contributes its dashboard routes
        from deeplearning4j_tpu.serve import httpcommon

        outer = self

        class Handler(httpcommon.ObservedHandler):
            inflight = httpcommon.InFlight()

            def handle_get(self) -> int:
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                route = parsed.path
                # ?lang=ja etc. (DefaultI18N setDefaultLanguage per request)
                lang = (parse_qs(parsed.query).get("lang") or [None])[0]
                if route in ("/", "/train", "/train/overview"):
                    # served pages are live: re-rendered per request + a
                    # 5s meta-refresh so the browser polls while training
                    body = outer.render_html(refresh_seconds=5,
                                             lang=lang).encode()
                    ctype = "text/html"
                elif route == "/train/system":
                    body = outer.render_system_html(lang=lang).encode()
                    ctype = "text/html"
                elif route == "/tsne":
                    body = outer.render_tsne_html(lang=lang).encode()
                    ctype = "text/html"
                elif route == "/stats":
                    body = json.dumps([
                        {"sessions": st.list_session_ids()} for st in outer.storages
                    ]).encode()
                    ctype = "application/json"
                elif route == "/debug/trace":
                    # live Chrome/Perfetto trace of the span ring + event
                    # log (load in ui.perfetto.dev / chrome://tracing)
                    from deeplearning4j_tpu.obs import trace_export

                    body = trace_export.live_trace(
                        include_events=True).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return 404
                return self.send_body(200, body, ctype)

            def handle_post(self) -> int:
                from urllib.parse import urlparse

                if urlparse(self.path).path == "/tsne":
                    # TsneModule upload parity: JSON {coords, labels?, name?}
                    try:
                        payload = self.read_json()
                        outer.upload_tsne(payload["coords"],
                                          payload.get("labels"),
                                          session_id=str(payload.get("name",
                                                                     "tsne")))
                    except Exception as e:
                        return self.send_body(400, str(e).encode(),
                                              "text/plain")
                    return self.send_body(200, b"ok", "text/plain")
                if urlparse(self.path).path != "/remote" \
                        or outer._remote_storage is None:
                    self.send_response(404)
                    self.end_headers()
                    return 404
                try:
                    payload = self.read_json()
                    records = payload if isinstance(payload, list) else [payload]
                    # validate the WHOLE batch before applying any record:
                    # a mid-batch failure must not store a partial batch the
                    # client will then retry in full (duplicates), and a
                    # record without session_id would poison every later
                    # dashboard read (list_session_ids keys on it)
                    if not all(isinstance(r, dict) and "session_id" in r
                               for r in records):
                        raise ValueError(
                            "records must be JSON objects with a session_id")
                    # fully parse/stage the batch BEFORE the first put_*:
                    # any VALIDATION failure leaves storage untouched (a
                    # storage fault mid-apply can still persist a prefix —
                    # put_* on validated dicts doesn't raise in the
                    # in-memory/file storages shipped here)
                    staged = [(rec.pop("_kind", "update"), rec)
                              for rec in records]
                except Exception as e:  # any bad payload -> 400, keep serving
                    return self.send_body(400, str(e).encode(), "text/plain")
                try:
                    for kind, rec in staged:
                        if kind == "static":
                            outer._remote_storage.put_static_info(rec)
                        else:
                            outer._remote_storage.put_update(rec)
                except Exception as e:  # storage fault: 500, keep serving
                    return self.send_body(500, str(e).encode(), "text/plain")
                return self.send_body(200, b"ok", "text/plain")

        self._httpd, self._thread, self.port = httpcommon.start_server(
            Handler, port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread:
                self._thread.join(timeout=10)
                self._thread = None
