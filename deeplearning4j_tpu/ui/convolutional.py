"""ConvolutionalIterationListener — activation-grid visualization.

Capability parity with the reference's
ui/weights/ConvolutionalIterationListener.java:38 (iterationDone:110
rasterizes each conv layer's activation channels into one image and streams
it to the UI). Redesigned for the jit world: activations are not observable
inside the compiled train step, so the listener re-runs an inference-mode
``feed_forward`` on a caller-provided probe batch every ``frequency``
iterations and writes per-layer channel grids as PNGs (pure-stdlib zlib
encoder — air-gapped, no PIL) plus an index HTML built from the
`ui/components.py` DSL.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import List, Optional

import numpy as np


def encode_png_gray(img: np.ndarray) -> bytes:
    """Minimal 8-bit grayscale PNG encoder (stdlib only). ``img``: [H,W]
    uint8."""
    img = np.asarray(img, np.uint8)
    if img.ndim != 2:
        raise ValueError(f"expected [H,W] grayscale, got shape {img.shape}")
    h, w = img.shape

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)  # 8-bit gray
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(h))
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw, 6)) + chunk(b"IEND", b""))


def activation_grid(act: np.ndarray, max_channels: int = 64,
                    border: int = 1) -> np.ndarray:
    """Tile an [H,W,C] activation into one ~square uint8 grid image, each
    channel min-max normalized independently (the reference rasterizes each
    channel as its own gray patch, rasterizeConvoLayers:181)."""
    act = np.asarray(act, np.float32)
    if act.ndim != 3:
        raise ValueError(f"expected [H,W,C], got shape {act.shape}")
    h, w, c = act.shape
    c = min(c, max_channels)
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    out = np.zeros((rows * (h + border) + border,
                    cols * (w + border) + border), np.uint8)
    for i in range(c):
        ch = act[:, :, i]
        lo, hi = float(ch.min()), float(ch.max())
        norm = (ch - lo) / (hi - lo) if hi > lo else np.zeros_like(ch)
        r, col = divmod(i, cols)
        y0 = border + r * (h + border)
        x0 = border + col * (w + border)
        out[y0:y0 + h, x0:x0 + w] = (norm * 255).astype(np.uint8)
    return out


class ConvolutionalIterationListener:
    """Every ``frequency`` iterations, renders channel grids of every
    conv-shaped (4-D) activation for ``probe_input`` into ``out_dir``.

    ``probe_input``: [1,H,W,C] (or [B,...]; only the first example is
    rendered, like the reference's minibatch slice)."""

    def __init__(self, probe_input, out_dir: str, frequency: int = 10,
                 max_channels: int = 64):
        if frequency < 1:
            raise ValueError(f"frequency must be >= 1: {frequency}")
        self.probe = np.asarray(probe_input)[:1]
        self.out_dir = out_dir
        self.frequency = frequency
        self.max_channels = max_channels
        self.rendered: List[str] = []
        os.makedirs(out_dir, exist_ok=True)

    # TrainingListener SPI ------------------------------------------------
    def on_epoch_start(self, model, epoch: int):
        pass

    def on_epoch_end(self, model, epoch: int):
        pass

    def on_gradient_calculation(self, model, iteration: int):
        pass

    def iteration_done(self, model, iteration: int, score: float,
                       batch_size: int = 0):
        if iteration % self.frequency != 0:
            return
        acts = model.feed_forward(self.probe, train=False)
        paths = []
        for li, a in enumerate(acts):
            a = np.asarray(a)
            if a.ndim != 4:  # only conv-shaped [B,H,W,C] activations
                continue
            grid = activation_grid(a[0], self.max_channels)
            p = os.path.join(self.out_dir, f"iter{iteration:06d}_layer{li}.png")
            with open(p, "wb") as f:
                f.write(encode_png_gray(grid))
            paths.append(p)
        self.rendered.extend(paths)
        self._write_index()

    def _write_index(self) -> None:
        from deeplearning4j_tpu.ui.components import (
            ComponentText, render_html)

        imgs = "".join(
            f'<div class="card"><h3>{os.path.basename(p)}</h3>'
            f'<img src="{os.path.basename(p)}"/></div>'
            for p in self.rendered)
        page = render_html(
            ComponentText("Convolutional activations (probe example 0)"),
            title="convolutional activations")
        page = page.replace("</body>", imgs + "</body>")
        with open(os.path.join(self.out_dir, "index.html"), "w") as f:
            f.write(page)
