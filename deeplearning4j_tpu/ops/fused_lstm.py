"""Weight-stationary fused LSTM scan — the CudnnLSTMHelper analog.

The reference accelerates its LSTMs with a fused cuDNN time loop
(deeplearning4j-cuda/.../CudnnLSTMHelper.java, 612 LoC; shared math in
LSTMHelpers.java:69,393). The TPU-native equivalent here is a Pallas
kernel that runs the WHOLE recurrence in one kernel invocation:

- The input projection x @ Wx + b is hoisted OUTSIDE (one [B*T, F] MXU
  matmul, exactly like the XLA path in nn/layers/recurrent.py).
- The kernel grids over time CHUNKS. TPU grids execute sequentially on a
  core, so VMEM scratch persists across grid steps: the recurrent weights
  Wh [H, 4H] stay resident in VMEM for the entire sequence (index_map
  pins their block), and the h/c carries live in f32 scratch — nothing
  recurrent touches HBM between timesteps. At the bench config
  (H=256 bf16) Wh is 0.5 MB — re-fetched from HBM every scan iteration
  by the XLA path, fetched ONCE here.
- Per chunk it writes the h outputs plus the (bf16) gate/cell residuals
  the backward needs.
- The backward is a second Pallas kernel over the REVERSED chunk grid:
  dh/dc ride in scratch, dWh accumulates in f32 scratch and is emitted on
  the final grid step, dzx streams out per chunk (the cotangent of the
  hoisted input projection — XLA autodiff handles Wx/b from there).

Masking follows the framework's recurrent contract exactly (masked steps
carry state through unchanged and output zeros — nn/layers/recurrent.py
``apply_seq``): the forward blends carries with the mask, the backward
routes carry-through cotangents around the gate path. Sequence padding
(T not a multiple of the chunk) is the same mechanism with mask rows 0.

Gate order is [i, f, g, o] (the framework's LSTM layout; DL4J's
[g, f, o, i] order is permuted at import time by modelimport/dl4j.py).
``interpret=True`` runs both kernels in the Pallas interpreter — the CPU
test path (tests/test_fused_lstm.py asserts equivalence against the
lax.scan oracle, forward and gradients, masked and unmasked).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _sig(x):
    return jax.nn.sigmoid(x)


def _fwd_kernel(zx_ref, wh_ref, h0_ref, c0_ref, m_ref, *rest,
                tc: int, H: int, n_chunks: int, has_peep: bool = False):
    """One time-chunk: zx [B, tc, 4H]; Wh [H, 4H] (resident); h0/c0 [B, H];
    m [B, tc]; optional peephole [1, 3H] (GravesLSTM: c_prev->i,f and
    c_new->o, LSTMHelpers.java:71); outputs hs/cs [B, tc, H] (post-mask
    carries), gates [B, tc, 4H] (pre-mask), final carries [B, H]. h/c
    persist in f32 scratch across the sequential chunk grid."""
    if has_peep:
        (peep_ref, hs_ref, gates_ref, cs_ref, hT_ref, cT_ref,
         h_scr, c_scr) = rest
    else:
        (hs_ref, gates_ref, cs_ref, hT_ref, cT_ref, h_scr, c_scr) = rest
        peep_ref = None
    ci = pl.program_id(0)

    @pl.when(ci == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    def step(t, _):
        h = h_scr[...]
        c = c_scr[...]
        zx_t = zx_ref[:, t, :].astype(jnp.float32)            # [B, 4H]
        z = zx_t + jnp.dot(h.astype(wh_ref.dtype), wh_ref[...],
                           preferred_element_type=jnp.float32)
        if peep_ref is not None:
            peep = peep_ref[...].astype(jnp.float32)          # [1, 3H]
            i = _sig(z[:, 0 * H:1 * H] + c * peep[:, 0 * H:1 * H])
            f = _sig(z[:, 1 * H:2 * H] + c * peep[:, 1 * H:2 * H])
            g = jnp.tanh(z[:, 2 * H:3 * H])
            c_new = f * c + i * g
            o = _sig(z[:, 3 * H:4 * H] + c_new * peep[:, 2 * H:3 * H])
        else:
            i = _sig(z[:, 0 * H:1 * H])
            f = _sig(z[:, 1 * H:2 * H])
            g = jnp.tanh(z[:, 2 * H:3 * H])
            o = _sig(z[:, 3 * H:4 * H])
            c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        m = m_ref[:, t][:, None].astype(jnp.float32)          # [B, 1]
        h_out = m * h_new + (1.0 - m) * h
        c_out = m * c_new + (1.0 - m) * c
        h_scr[...] = h_out
        c_scr[...] = c_out
        hs_ref[:, t, :] = h_out.astype(hs_ref.dtype)
        cs_ref[:, t, :] = c_out.astype(cs_ref.dtype)
        gates_ref[:, t, :] = jnp.concatenate(
            [i, f, g, o], axis=-1).astype(gates_ref.dtype)
        return 0

    lax.fori_loop(0, tc, step, 0, unroll=True)

    @pl.when(ci == n_chunks - 1)
    def _final():
        hT_ref[...] = h_scr[...].astype(hT_ref.dtype)
        cT_ref[...] = c_scr[...].astype(cT_ref.dtype)


def _bwd_kernel(gates_ref, cs_ref, cprev_ref, hprev_ref, wh_ref, m_ref,
                dhs_ref, dcT_ref, *rest,
                tc: int, H: int, n_chunks: int, has_peep: bool = False):
    """Reverse-grid chunk: consumes the forward residuals and the output
    cotangent dhs; emits dzx per chunk and (on the last grid step = time
    chunk 0) dWh / dh0 / dc0 (+ dpeephole). dh/dc/dWh (+dpeep) persist in
    f32 scratch; the final-carry cotangents seed them (dhT is folded into
    dhs[T-1] by the caller — h_T IS hs[:, T-1] — and dcT seeds the dc
    scratch here)."""
    if has_peep:
        (peep_ref, dzx_ref, dwh_ref, dh0_ref, dc0_ref, dpeep_ref,
         dh_scr, dc_scr, dwh_scr, dpeep_scr) = rest
    else:
        (dzx_ref, dwh_ref, dh0_ref, dc0_ref,
         dh_scr, dc_scr, dwh_scr) = rest
        peep_ref = dpeep_ref = dpeep_scr = None
    ci = pl.program_id(0)

    @pl.when(ci == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = dcT_ref[...].astype(jnp.float32)
        dwh_scr[...] = jnp.zeros_like(dwh_scr)
        if dpeep_scr is not None:
            dpeep_scr[...] = jnp.zeros_like(dpeep_scr)

    def step(k, _):
        t = tc - 1 - k
        gates = gates_ref[:, t, :].astype(jnp.float32)
        i = gates[:, 0 * H:1 * H]
        f = gates[:, 1 * H:2 * H]
        g = gates[:, 2 * H:3 * H]
        o = gates[:, 3 * H:4 * H]
        c_t = cs_ref[:, t, :].astype(jnp.float32)
        c_prev = cprev_ref[:, t, :].astype(jnp.float32)
        m = m_ref[:, t][:, None].astype(jnp.float32)

        # total cotangents on (h_t, c_t): carry + this step's output
        # (the layer's emitted output is hs * m, so its cotangent arrives
        # here already multiplied by m by the caller)
        A = dh_scr[...] + dhs_ref[:, t, :].astype(jnp.float32)
        C = dc_scr[...]

        tanh_c = jnp.tanh(c_t)
        dh_g = A * m                       # gate-path share
        do = dh_g * tanh_c * o * (1.0 - o)          # dz_o (a-level)
        dcg = C * m + dh_g * o * (1.0 - tanh_c * tanh_c)
        if peep_ref is not None:
            peep = peep_ref[...].astype(jnp.float32)          # [1, 3H]
            # o = sig(z_o + c_new * p_o): its c_new dependence feeds dcg
            dcg = dcg + do * peep[:, 2 * H:3 * H]
        di = dcg * g * i * (1.0 - i)
        dg = dcg * i * (1.0 - g * g)
        df = dcg * c_prev * f * (1.0 - f)
        dz = jnp.concatenate([di, df, dg, do], axis=-1)       # [B, 4H]

        dzx_ref[:, t, :] = dz.astype(dzx_ref.dtype)
        h_prev = hprev_ref[:, t, :].astype(jnp.float32)
        dwh_scr[...] += jnp.dot(h_prev.astype(wh_ref.dtype).T,
                                dz.astype(wh_ref.dtype),
                                preferred_element_type=jnp.float32)
        dh_new = jnp.dot(dz.astype(wh_ref.dtype), wh_ref[...].T,
                         preferred_element_type=jnp.float32) + A * (1.0 - m)
        dc_new = dcg * f + C * (1.0 - m)
        if peep_ref is not None:
            # i/f peepholes read c_prev: route their a-level cotangents
            # into dc_{t-1}; accumulate the [3H] peephole grads
            dc_new = dc_new + di * peep[:, 0 * H:1 * H] \
                + df * peep[:, 1 * H:2 * H]
            dpeep_scr[...] += jnp.concatenate([
                jnp.sum(di * c_prev, axis=0, keepdims=True),
                jnp.sum(df * c_prev, axis=0, keepdims=True),
                jnp.sum(do * c_t, axis=0, keepdims=True),
            ], axis=-1)                                       # [1, 3H]
        dh_scr[...] = dh_new
        dc_scr[...] = dc_new
        return 0

    lax.fori_loop(0, tc, step, 0, unroll=True)

    @pl.when(ci == n_chunks - 1)
    def _final():
        dwh_ref[...] = dwh_scr[...].astype(dwh_ref.dtype)
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_scr[...].astype(dc0_ref.dtype)
        if dpeep_ref is not None:
            dpeep_ref[...] = dpeep_scr[...].astype(dpeep_ref.dtype)


def _pick_chunk(T: int, B: int, H: int, itemsize: int) -> int:
    """Time-chunk size: bounded by the VMEM block budget AND an absolute
    ceiling (the kernels fully unroll the chunk — unbounded tc would blow
    up compile time). Prefers divisors of T (no padding); falls back to
    the padded path when T's divisors are all degenerate (prime T)."""
    # per-timestep block bytes: zx 4H + gates 4H + hs H + cs H (+ cprev,
    # hprev, dzx in the backward: budget 16H per step to be safe)
    per_t = B * 16 * H * itemsize
    cap = max(1, min(32, int((6 * 2 ** 20) // max(per_t, 1))))
    best = 1
    for tc in range(1, min(T, cap) + 1):
        if T % tc == 0:
            best = tc
    if best >= max(cap // 2, 1) or best == T:
        return best
    return cap  # non-divisor: callers pad T with mask-0 rows


def _pad_time(x, T_pad):
    if x.shape[1] == T_pad:
        return x
    cfg = [(0, 0)] * x.ndim
    cfg[1] = (0, T_pad - x.shape[1])
    return jnp.pad(x, cfg)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _fused(zx, wh, h0, c0, mask, peephole, interpret):
    out, _res = _fused_fwd(zx, wh, h0, c0, mask, peephole, interpret)
    return out


def _fwd_call(zx, wh, h0, c0, mask, peephole, interpret, tc):
    B, T, Z = zx.shape
    H = Z // 4
    n_chunks = T // tc
    kw = {}
    if _VMEM is not None and not interpret:
        kw["memory_space"] = _VMEM
    blk_t = lambda ci: (0, ci, 0)        # noqa: E731
    pin = lambda ci: (0, 0)              # noqa: E731
    kernel = functools.partial(_fwd_kernel, tc=tc, H=H, n_chunks=n_chunks,
                               has_peep=peephole is not None)
    in_specs = [
        pl.BlockSpec((B, tc, Z), blk_t, **kw),
        pl.BlockSpec((H, Z), pin, **kw),
        pl.BlockSpec((B, H), pin, **kw),
        pl.BlockSpec((B, H), pin, **kw),
        pl.BlockSpec((B, tc), lambda ci: (0, ci), **kw),
    ]
    args = [zx, wh, h0, c0, mask]
    if peephole is not None:
        in_specs.append(pl.BlockSpec((1, 3 * H), pin, **kw))
        args.append(peephole.reshape(1, 3 * H))
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((B, tc, H), blk_t, **kw),
            pl.BlockSpec((B, tc, Z), blk_t, **kw),
            pl.BlockSpec((B, tc, H), blk_t, **kw),
            pl.BlockSpec((B, H), pin, **kw),
            pl.BlockSpec((B, H), pin, **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, H), zx.dtype),       # hs (carries)
            # residuals in the INPUT precision: exact f32 when training
            # f32, half-bandwidth when the model is bf16
            jax.ShapeDtypeStruct((B, T, Z), zx.dtype),       # gate residuals
            jax.ShapeDtypeStruct((B, T, H), zx.dtype),       # cell residuals
            jax.ShapeDtypeStruct((B, H), zx.dtype),          # final h
            jax.ShapeDtypeStruct((B, H), zx.dtype),          # final c
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ] if pltpu is not None else [],
        interpret=interpret,
    )(*args)


def _fused_fwd(zx, wh, h0, c0, mask, peephole, interpret):
    B, T, Z = zx.shape
    H = Z // 4
    tc = _pick_chunk(T, B, H, jnp.dtype(zx.dtype).itemsize)
    T_pad = ((T + tc - 1) // tc) * tc
    zx_p = _pad_time(zx, T_pad)
    m = jnp.ones((B, T), zx.dtype) if mask is None else mask.astype(zx.dtype)
    m_p = _pad_time(m, T_pad)          # padded steps: mask 0 = carry freeze
    hs, gates, cs, hT, cT = _fwd_call(zx_p, wh, h0, c0, m_p, peephole,
                                      interpret, tc)
    hs = hs[:, :T]
    out = hs * m[..., None] if mask is not None else hs
    # zx itself is NOT a backward residual: the gates carry everything the
    # reverse sweep needs (keeping zx alive would hold an extra [B,T,4H]
    # HBM buffer across the step for nothing)
    return ((out, (hT, cT)),
            (gates[:, :T], wh, h0, c0, mask, peephole, hs, cs[:, :T]))


def _bwd_call(gates, cs, cprev, hprev, wh, m, dhs, dcT, peephole,
              interpret, tc):
    B, T, Z = gates.shape
    H = Z // 4
    n_chunks = T // tc
    kw = {}
    if _VMEM is not None and not interpret:
        kw["memory_space"] = _VMEM
    rev_t = lambda ci: (0, n_chunks - 1 - ci, 0)   # noqa: E731
    rev_m = lambda ci: (0, n_chunks - 1 - ci)      # noqa: E731
    pin = lambda ci: (0, 0)                        # noqa: E731
    has_peep = peephole is not None
    kernel = functools.partial(_bwd_kernel, tc=tc, H=H, n_chunks=n_chunks,
                               has_peep=has_peep)
    in_specs = [
        pl.BlockSpec((B, tc, Z), rev_t, **kw),
        pl.BlockSpec((B, tc, H), rev_t, **kw),
        pl.BlockSpec((B, tc, H), rev_t, **kw),
        pl.BlockSpec((B, tc, H), rev_t, **kw),
        pl.BlockSpec((H, Z), pin, **kw),
        pl.BlockSpec((B, tc), rev_m, **kw),
        pl.BlockSpec((B, tc, H), rev_t, **kw),
        pl.BlockSpec((B, H), pin, **kw),
    ]
    args = [gates, cs, cprev, hprev, wh, m, dhs, dcT]
    out_specs = [
        pl.BlockSpec((B, tc, Z), rev_t, **kw),
        pl.BlockSpec((H, Z), pin, **kw),
        pl.BlockSpec((B, H), pin, **kw),
        pl.BlockSpec((B, H), pin, **kw),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((B, T, Z), jnp.float32),    # dzx
        jax.ShapeDtypeStruct((H, Z), jnp.float32),       # dWh
        jax.ShapeDtypeStruct((B, H), jnp.float32),       # dh0
        jax.ShapeDtypeStruct((B, H), jnp.float32),       # dc0
    ]
    scratch = [
        pltpu.VMEM((B, H), jnp.float32),
        pltpu.VMEM((B, H), jnp.float32),
        pltpu.VMEM((H, Z), jnp.float32),
    ] if pltpu is not None else []
    if has_peep:
        in_specs.append(pl.BlockSpec((1, 3 * H), pin, **kw))
        args.append(peephole.reshape(1, 3 * H))
        out_specs.append(pl.BlockSpec((1, 3 * H), pin, **kw))
        out_shape.append(jax.ShapeDtypeStruct((1, 3 * H), jnp.float32))
        if pltpu is not None:
            scratch.append(pltpu.VMEM((1, 3 * H), jnp.float32))
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)


def _fused_bwd(interpret, res, cts):
    (dout, (dhT, dcT)) = cts
    gates, wh, h0, c0, mask, peephole, hs, cs = res
    zx_dtype = hs.dtype              # hs was emitted in zx's dtype
    B, T, Z = gates.shape
    H = Z // 4
    tc = _pick_chunk(T, B, H, jnp.dtype(zx_dtype).itemsize)
    T_pad = ((T + tc - 1) // tc) * tc

    m = jnp.ones((B, T), jnp.float32) if mask is None \
        else mask.astype(jnp.float32)
    # the layer output is hs * m: fold m into the output cotangent, and
    # seed the final-carry cotangents into the LAST timestep's carry slot
    dhs = dout.astype(jnp.float32) * m[..., None]
    # shifted carries: value entering step t
    hprev = jnp.concatenate([h0.astype(hs.dtype)[:, None], hs[:, :-1]], 1)
    cprev = jnp.concatenate([c0.astype(jnp.float32)[:, None],
                             cs[:, :-1].astype(jnp.float32)], 1)

    pad = lambda a: _pad_time(a, T_pad)
    # the final-carry cotangents enter the reverse sweep exactly: h_T IS
    # hs[:, T-1] (post-mask), so dhT folds into the last timestep's dhs
    # row (the kernel adds dhs[t] to the carry WITHOUT the mask factor);
    # dcT seeds the kernel's dc scratch at the first reverse chunk.
    dhs = dhs.at[:, T - 1].add(dhT.astype(jnp.float32))
    outs = _bwd_call(
        pad(gates), pad(cs), pad(cprev), pad(hprev), wh,
        pad(m), pad(dhs), dcT.astype(jnp.float32), peephole, interpret, tc)
    if peephole is not None:
        dzx_p, dwh, dh0, dc0, dpeep = outs
        dpeep = dpeep.reshape(3 * H).astype(peephole.dtype)
    else:
        dzx_p, dwh, dh0, dc0 = outs
        dpeep = None
    dzx = dzx_p[:, :T]
    return dzx.astype(zx_dtype), dwh.astype(wh.dtype), \
        dh0.astype(h0.dtype), dc0.astype(c0.dtype), \
        (jnp.zeros_like(mask) if mask is not None else None), dpeep


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_lstm(zx, wh, h0, c0, mask=None, peephole=None, *,
               interpret: bool = False):
    """Weight-stationary LSTM recurrence over precomputed input rows.

    zx: [B, T, 4H] (= x @ Wx + b, gate order [i, f, g, o]);
    wh: [H, 4H]; h0/c0: [B, H]; mask: optional [B, T] (masked steps carry
    state through and output zeros — the framework's recurrent contract);
    peephole: optional [3H] = [p_i | p_f | p_o] (GravesLSTM: c_prev feeds
    i and f, c_new feeds o — LSTMHelpers.java:71).
    Returns (outputs [B, T, H], (h_T, c_T)). Differentiable (custom VJP,
    blockwise Pallas backward); BOTH final-carry cotangents are exact —
    dhT folds into the last timestep's output row, dcT seeds the reverse
    sweep's dc scratch (test_fused_lstm.py differentiates through both).
    """
    if mask is not None:
        mask = jnp.asarray(mask, jnp.float32)
    return _fused(zx, wh, h0, c0, mask, peephole, interpret)
