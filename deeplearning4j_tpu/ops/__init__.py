"""Custom TPU kernels (Pallas).

The compute path is XLA by design (SURVEY.md §7: "Pallas only where XLA
underperforms"); this package holds the exceptions. Currently:

- :mod:`flash_attention` — blockwise-softmax attention forward that never
  materialises the [T, T] score matrix (the XLA path's HBM bottleneck for
  long sequences).
"""

from deeplearning4j_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
