"""Flash attention — Pallas TPU kernel with online (streaming) softmax.

The XLA attention path (`parallel/ring.py local_attention`) materialises
the [B, H, T, T] score matrix in HBM; at long T that traffic dominates
(the framework's ResNet-style roofline analysis, docs/PERF.md, shows HBM
bandwidth is the binding resource on this chip). This kernel computes
attention blockwise in VMEM — scores never leave the chip — using the
standard streaming-softmax recurrence (running max m, normaliser l,
rescaled accumulator), one (batch*head, q-block) program per grid cell
looping over key blocks.

Beyond-reference scope: the reference (DL4J 0.9.2) has no attention layer
at all (SURVEY.md §5.7); this accelerates the framework's TransformerLM
extension. Training uses a custom VJP whose backward is ALSO blockwise
Pallas (FlashAttention-2 style): the forward emits a per-row logsumexp
residual, the dq kernel grids over q-blocks and the dk/dv kernel over
k-blocks, each rebuilding p = exp(s - lse) in VMEM — no [T, T] tensor in
either direction. A rematerialising XLA backward (``bwd="xla"``) remains
as the correctness oracle and fallback.

CPU/tests: ``interpret=True`` runs the identical kernel in the Pallas
interpreter; the layer's default ("auto") uses the kernel only on TPU and
falls back to the XLA path elsewhere. Key-validity masks (padded batches)
run IN the kernel: a [B, T] kmask contributes one [1, block_k] row load
per key block, ANDed into the causal/length validity mask (round 5).
Attention dropout is applied to the attention OUTPUT (not the probability
matrix) in both paths — see MultiHeadAttention.apply in
nn/layers/attention.py — so dropout is flash-compatible and does not gate
the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_BIG = -1e30


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _kernel(q_ref, k_ref, v_ref, *rest, block_q: int,
            block_k: int, t_real: int, t_pad: int, causal: bool,
            scale: float, q_off: int = 0, k_off: int = 0,
            has_kmask: bool = False):
    """One q-block vs all key blocks. Refs: q [1, block_q, D];
    k/v [1, t_pad, D]; optional kmask [1, 1, t_pad] (row layout, per
    BATCH — key validity, ANDed into ``valid``); o [1, block_q, D];
    lse [1, 1, block_q].

    lse is stored as a ROW over a [BH, 1, t_pad] array: the natural
    column layout ([.., t_pad, 1]) lane-pads 128x on TPU, which as a
    per-layer vjp residual OOMs large models; the row layout only
    sublane-pads 8x. NOTE: zero-padded q rows get a real finite lse (they
    still see valid keys); the backward's q_valid mask — not any lse
    sentinel — is what keeps padded rows out of dk/dv."""
    if has_kmask:
        km_ref, o_ref, lse_ref = rest
    else:
        (o_ref, lse_ref), km_ref = rest, None
    qi = pl.program_id(1)
    # operands stay in their native dtype (bf16 keeps the MXU at full rate);
    # scores, softmax state and the accumulator are f32. q_off/k_off are
    # ABSOLUTE sequence offsets (ring/chunked attention blocks).
    q = q_ref[0]                                                 # [bq, D]
    d = q.shape[-1]
    q_pos = q_off + qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)                              # [bq, 1]

    m0 = jnp.full((block_q, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T,
                    preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_pos = k_off + kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)                          # [1, bk]
        valid = k_pos < k_off + t_real
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        if km_ref is not None:
            km = km_ref[0, :, pl.ds(kb * block_k, block_k)]      # [1, bk]
            valid = jnp.logical_and(valid, km > 0)
        s = jnp.where(valid, s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                                   # [bq, bk] f32
        alpha = jnp.exp(m - m_new)                               # [bq, 1]
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return m_new, l, acc

    n_kb = t_pad // block_k
    if causal and q_off == k_off:
        # key blocks strictly above the diagonal contribute nothing: stop
        # after the block containing this q-block's last position. Equal
        # offsets (incl. the ring schedule's diagonal chunk) reduce
        # k_pos <= q_pos to the same local comparison as the unshifted
        # case; for unequal offsets masking alone stays correct.
        n_kb = jnp.minimum(n_kb, (qi + 1) * block_q // block_k
                           + (1 if block_q % block_k else 0))
    m, l, acc = lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).reshape(1, block_q)


def _pad_bh(x, t_pad):
    """[B, T, H, D] -> [B*H, t_pad, D]."""
    B, T, H, D = x.shape
    x = jnp.swapaxes(x, 1, 2).reshape(B * H, T, D)
    if t_pad != T:
        x = jnp.pad(x, ((0, 0), (0, t_pad - T), (0, 0)))
    return x


def _from_bh(x, B, T, H):
    x = x[:, :T].reshape(B, H, T, x.shape[-1])
    return jnp.swapaxes(x, 1, 2)


def _block_sizes(T, block_q, block_k):
    bq = min(block_q, max(T, 1))
    bk = min(block_k, max(T, 1))
    t_pad = _cdiv(T, bq) * bq
    t_pad = _cdiv(t_pad, bk) * bk
    return bq, bk, t_pad


def _block_sizes2(Tq, Tk, block_q, block_k):
    """Independent q/k lengths (chunked blocks): (bq, bk, q_pad, k_pad)."""
    bq = min(block_q, max(Tq, 1))
    bk = min(block_k, max(Tk, 1))
    return bq, bk, _cdiv(Tq, bq) * bq, _cdiv(Tk, bk) * bk


def _fwd_pallas_call(qt, kt, vt, *, D, bq, bk, q_pad, k_pad, t_real_k,
                     causal, scale, q_off, k_off, interpret, dtype,
                     kmask=None, H=1):
    """The shared forward pallas_call (main path and chunked-block path):
    padded [BH, q_pad, D] q and [BH, k_pad, D] k/v -> ([BH, q_pad, D] out,
    [BH, 1, q_pad] row-layout lse). ``kmask``: optional [B, 1, k_pad] f32
    key-validity rows, shared by the H heads of each batch (the grid's bh
    axis maps to batch bh // H)."""
    BH = qt.shape[0]
    kernel = functools.partial(
        _kernel, block_q=bq, block_k=bk, t_real=t_real_k, t_pad=k_pad,
        causal=causal, scale=scale, q_off=q_off, k_off=k_off,
        has_kmask=kmask is not None)
    kw = {}
    if _VMEM is not None and not interpret:
        kw["memory_space"] = _VMEM
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0), **kw),
        pl.BlockSpec((1, k_pad, D), lambda bh, qi: (bh, 0, 0), **kw),
        pl.BlockSpec((1, k_pad, D), lambda bh, qi: (bh, 0, 0), **kw),
    ]
    args = [qt, kt, vt]
    if kmask is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, k_pad), lambda bh, qi: (bh // H, 0, 0), **kw))
        args.append(kmask)
    return pl.pallas_call(
        kernel,
        grid=(BH, q_pad // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0), **kw),
            pl.BlockSpec((1, 1, bq), lambda bh, qi: (bh, 0, qi), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, q_pad, D), dtype),
            jax.ShapeDtypeStruct((BH, 1, q_pad), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


def _pad_km(kmask, k_pad):
    """[B, Tk] key-validity -> [B, 1, k_pad] f32 rows (padding keys 0)."""
    B, Tk = kmask.shape
    km = kmask.astype(jnp.float32).reshape(B, 1, Tk)
    if k_pad != Tk:
        km = jnp.pad(km, ((0, 0), (0, 0), (0, k_pad - Tk)))
    return km


def _flash_raw(q, k, v, kmask, causal: bool, block_q: int, block_k: int,
               interpret: bool, with_lse: bool = False):
    """q/k/v: [B, T, H, D] -> [B, T, H, D] (plus the [B*H, 1, t_pad] row
    logsumexp when ``with_lse``). Forward only. ``kmask``: [B, T] key
    validity or None."""
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    bq, bk, t_pad = _block_sizes(T, block_q, block_k)
    qt, kt, vt = (_pad_bh(x, t_pad) for x in (q, k, v))
    km = _pad_km(kmask, t_pad) if kmask is not None else None
    out, lse = _fwd_pallas_call(
        qt, kt, vt, D=D, bq=bq, bk=bk, q_pad=t_pad, k_pad=t_pad, t_real_k=T,
        causal=causal, scale=scale, q_off=0, k_off=0, interpret=interpret,
        dtype=q.dtype, kmask=km, H=H)
    res = _from_bh(out, B, T, H)
    return (res, lse) if with_lse else res


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   block_q: int, block_k: int, t_real_q: int,
                   t_real_k: int, k_pad: int, causal: bool, scale: float,
                   q_off: int = 0, k_off: int = 0, has_kmask: bool = False):
    """dq for one q-block: dq = scale * sum_k [p * (do@v^T - delta)] @ k,
    p = exp(q@k^T*scale - lse) (FlashAttention-2 backward, eq. dS).
    ``delta`` may already carry the -dlse shift (differentiable-lse path:
    ds = p * (dp - delta + dlse)). Validity masks use LOCAL positions vs
    t_real_q/t_real_k; the causal comparison uses ABSOLUTE positions
    (q_off/k_off — chunked/ring blocks). Optional kmask ref [1, 1, k_pad]
    per batch ANDs into validity, mirroring the forward."""
    if has_kmask:
        km_ref, dq_ref = rest
    else:
        (dq_ref,), km_ref = rest, None
    qi = pl.program_id(1)
    q = q_ref[0]                                                 # [bq, D]
    do = do_ref[0]                                               # [bq, D]
    lse = lse_ref[0].reshape(block_q, 1)                         # row -> col
    delta = delta_ref[0].reshape(block_q, 1)
    q_loc = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)
    q_valid = q_loc < t_real_q

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_loc = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = jnp.logical_and(k_loc < t_real_k, q_valid)
        if causal:
            valid = jnp.logical_and(valid,
                                    k_off + k_loc <= q_off + q_loc)
        if km_ref is not None:
            km = km_ref[0, :, pl.ds(kb * block_k, block_k)]      # [1, bk]
            valid = jnp.logical_and(valid, km > 0)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)              # [bq, bk]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    n_kb = k_pad // block_k
    if causal and q_off == k_off:
        n_kb = jnp.minimum(n_kb, (qi + 1) * block_q // block_k
                           + (1 if block_q % block_k else 0))
    dq0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = lax.fori_loop(0, n_kb, body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, block_q: int, block_k: int,
                    t_real_q: int, t_real_k: int, q_pad: int, causal: bool,
                    scale: float, q_off: int = 0, k_off: int = 0,
                    has_kmask: bool = False):
    """dk/dv for one k-block, looping over q-blocks:
    dv = sum_q p^T @ do;  dk = scale * sum_q [p*(do@v^T - delta)]^T @ q.
    Same delta/offset semantics as _bwd_dq_kernel. Optional kmask ref
    [1, 1, block_k] (THIS k-block's validity slice, per batch)."""
    if has_kmask:
        km_ref, dk_ref, dv_ref = rest
    else:
        (dk_ref, dv_ref), km_ref = rest, None
    ki = pl.program_id(1)
    k = k_ref[0]                                                 # [bk, D]
    v = v_ref[0]
    k_loc = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)                              # [1, bk]
    k_valid = k_loc < t_real_k
    if km_ref is not None:
        k_valid = jnp.logical_and(k_valid, km_ref[0] > 0)        # [1, bk]

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, :, pl.ds(qb * block_q, block_q)
                      ].reshape(block_q, 1)                      # row -> col
        delta = delta_ref[0, :, pl.ds(qb * block_q, block_q)].reshape(
            block_q, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        q_loc = qb * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        valid = jnp.logical_and(k_valid, q_loc < t_real_q)
        if causal:
            valid = jnp.logical_and(valid,
                                    k_off + k_loc <= q_off + q_loc)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)              # [bq, bk]
        pc = p.astype(do.dtype)
        dv = dv + jnp.dot(pc.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    n_qb = q_pad // block_q
    qb_start = 0
    if causal and q_off == k_off:
        # q blocks strictly above this k block's first row see none of it
        qb_start = (ki * block_k) // block_q
    zeros = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dk, dv = lax.fori_loop(qb_start, n_qb, body, (zeros, zeros))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_pallas_calls(qt, kt, vt, dot, lse, delta, *, D, bq, bk, q_pad,
                      k_pad, t_real_q, t_real_k, causal, scale, q_off,
                      k_off, interpret, dtype, kmask=None, H=1):
    """The two backward pallas_calls over padded [BH, ., D] arrays; returns
    padded (dq, dk, dv). ``delta`` may already carry the -dlse shift.
    ``kmask``: optional [B, 1, k_pad] f32 rows (per batch; bh // H)."""
    BH = qt.shape[0]
    kw = {}
    if _VMEM is not None and not interpret:
        kw["memory_space"] = _VMEM
    full = lambda bh, i: (bh, 0, 0)          # noqa: E731
    blkq = lambda bh, i: (bh, i, 0)          # noqa: E731
    row = lambda bh, i: (bh, 0, i)           # noqa: E731
    has_km = kmask is not None

    dq_in_specs = [
        pl.BlockSpec((1, bq, D), blkq, **kw),
        pl.BlockSpec((1, k_pad, D), full, **kw),
        pl.BlockSpec((1, k_pad, D), full, **kw),
        pl.BlockSpec((1, bq, D), blkq, **kw),
        pl.BlockSpec((1, 1, bq), row, **kw),
        pl.BlockSpec((1, 1, bq), row, **kw),
    ]
    dq_args = [qt, kt, vt, dot, lse, delta]
    if has_km:
        dq_in_specs.append(
            pl.BlockSpec((1, 1, k_pad), lambda bh, i: (bh // H, 0, 0), **kw))
        dq_args.append(kmask)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=bq, block_k=bk,
                          t_real_q=t_real_q, t_real_k=t_real_k, k_pad=k_pad,
                          causal=causal, scale=scale, q_off=q_off,
                          k_off=k_off, has_kmask=has_km),
        grid=(BH, q_pad // bq),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, D), blkq, **kw),
        out_shape=jax.ShapeDtypeStruct((BH, q_pad, D), dtype),
        interpret=interpret,
    )(*dq_args)

    blkk = lambda bh, i: (bh, i, 0)          # noqa: E731
    dkv_in_specs = [
        pl.BlockSpec((1, q_pad, D), full, **kw),
        pl.BlockSpec((1, bk, D), blkk, **kw),
        pl.BlockSpec((1, bk, D), blkk, **kw),
        pl.BlockSpec((1, q_pad, D), full, **kw),
        pl.BlockSpec((1, 1, q_pad), full, **kw),
        pl.BlockSpec((1, 1, q_pad), full, **kw),
    ]
    dkv_args = [qt, kt, vt, dot, lse, delta]
    if has_km:
        dkv_in_specs.append(
            pl.BlockSpec((1, 1, bk), lambda bh, i: (bh // H, 0, i), **kw))
        dkv_args.append(kmask)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=bq, block_k=bk,
                          t_real_q=t_real_q, t_real_k=t_real_k, q_pad=q_pad,
                          causal=causal, scale=scale, q_off=q_off,
                          k_off=k_off, has_kmask=has_km),
        grid=(BH, k_pad // bk),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, D), blkk, **kw),
            pl.BlockSpec((1, bk, D), blkk, **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, k_pad, D), dtype),
            jax.ShapeDtypeStruct((BH, k_pad, D), dtype),
        ],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


def _row_layout(x2d, B, H, T, t_pad):
    """[B, H, T] f32 -> padded [B*H, 1, t_pad] row layout."""
    r = x2d.reshape(B * H, 1, T).astype(jnp.float32)
    if t_pad != T:
        r = jnp.pad(r, ((0, 0), (0, 0), (0, t_pad - T)))
    return r


def _flash_bwd_pallas(q, k, v, kmask, o, lse, g, causal: bool, block_q: int,
                      block_k: int, interpret: bool):
    """Blockwise backward: scores are rebuilt in VMEM from q/k/v and the
    forward's row-layout logsumexp — no [T, T] tensor ever reaches HBM."""
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    bq, bk, t_pad = _block_sizes(T, block_q, block_k)

    qt, kt, vt, dot = (_pad_bh(x, t_pad) for x in (q, k, v, g))
    km = _pad_km(kmask, t_pad) if kmask is not None else None
    # delta_i = rowsum(do_i * o_i): cheap elementwise XLA, f32; same
    # [BH, 1, t_pad] row layout as lse
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = _row_layout(jnp.swapaxes(delta, 1, 2), B, H, T, t_pad)

    dq, dk, dv = _bwd_pallas_calls(
        qt, kt, vt, dot, lse, delta, D=D, bq=bq, bk=bk, q_pad=t_pad,
        k_pad=t_pad, t_real_q=T, t_real_k=T, causal=causal, scale=scale,
        q_off=0, k_off=0, interpret=interpret, dtype=q.dtype, kmask=km, H=H)
    return (_from_bh(dq, B, T, H), _from_bh(dk, B, T, H),
            _from_bh(dv, B, T, H))


def _reference(q, k, v, causal: bool, kmask=None):
    """The same math in plain XLA ops — used by the equivalence tests.
    Matches parallel/ring.py local_attention semantics incl. the
    fully-masked-row clamp."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk",
                   q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[1]
        msk = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(msk[None, None], s, _NEG_BIG)
    if kmask is not None:
        s = jnp.where(kmask[:, None, None, :] > 0, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _reference_chunked(q, k, v, causal: bool, chunk: int = 128, kmask=None):
    """Attention computed q-chunk-at-a-time with ``lax.map`` — identical
    math to :func:`_reference`, but only [B, H, chunk, T] scores exist at
    once. The custom VJP differentiates THIS function, so the backward is
    memory-bounded too (vjp of lax.map is a scan with per-chunk residuals)
    and training works at the long T the flash forward enables."""
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    n = _cdiv(T, chunk)
    t_pad = n * chunk
    qp = jnp.pad(q, ((0, 0), (0, t_pad - T), (0, 0), (0, 0)))
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    k_pos = jnp.arange(T)

    def one_chunk(ci):
        qc = lax.dynamic_slice_in_dim(qp, ci * chunk, chunk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qc.astype(jnp.float32), kf) * scale
        q_pos = ci * chunk + jnp.arange(chunk)
        valid = jnp.ones((chunk, T), bool)
        if causal:
            valid = k_pos[None, :] <= q_pos[:, None]
        s = jnp.where(valid[None, None], s, _NEG_BIG)
        if kmask is not None:
            s = jnp.where(kmask[:, None, None, :] > 0, s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vf)        # [B,chunk,H,D]

    out = lax.map(one_chunk, jnp.arange(n))                # [n,B,chunk,H,D]
    out = jnp.moveaxis(out, 0, 1).reshape(B, t_pad, H, D)
    return out[:, :T].astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kmask, causal, block_q, block_k, interpret, bwd):
    return _flash_raw(q, k, v, kmask, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, kmask, causal, block_q, block_k, interpret, bwd):
    if bwd == "pallas":
        out, lse = _flash_raw(q, k, v, kmask, causal, block_q, block_k,
                              interpret, with_lse=True)
        return out, (q, k, v, kmask, out, lse)
    # the xla fallback exists for memory-constrained cases: don't burden it
    # with the out/lse residuals it never reads
    out = _flash_raw(q, k, v, kmask, causal, block_q, block_k, interpret)
    return out, (q, k, v, kmask, None, None)


def _flash_bwd(causal, block_q, block_k, interpret, bwd, res, g):
    q, k, v, kmask, o, lse = res
    dkm = (jnp.zeros_like(kmask) if kmask is not None else None)
    if bwd == "pallas":
        dq, dk, dv = _flash_bwd_pallas(q, k, v, kmask, o, lse, g, causal,
                                       block_q, block_k, interpret)
        return dq, dk, dv, dkm
    # XLA rematerialisation fallback (also the correctness oracle in
    # tests). Chunking is a memory/throughput trade: lax.map serialises
    # chunks (~15% slower at T=2048), so use the dense [T,T] recompute
    # while the f32 score tensor is affordable and switch to q-chunks only
    # when it is not.
    B, T, H, _ = q.shape
    if kmask is not None:
        # agree with the Pallas backward on fully-masked query rows: the
        # kernel's validity mask makes their p (hence dq and their dk/dv
        # contributions) exactly zero, while _reference's softmax over an
        # all-_NEG_BIG row is uniform — zero those rows' cotangent here
        has_valid = (jnp.cumsum(kmask, axis=1) > 0) if causal else \
            (jnp.sum(kmask, axis=1, keepdims=True) > 0)          # [B, T]/[B,1]
        g = g * has_valid[:, :, None, None].astype(g.dtype)
    score_bytes = 4 * B * H * T * T
    # the dense vjp holds ~3 score-sized f32 tensors at once (softmax
    # residual p + dp/ds temporaries), so budget for 3x, not 1x
    if 3 * score_bytes <= 4 << 30:
        fn = lambda q_, k_, v_: _reference(q_, k_, v_, causal, kmask)
    else:
        fn = lambda q_, k_, v_: _reference_chunked(q_, k_, v_, causal,
                                                   kmask=kmask)
    _, vjp = jax.vjp(fn, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, dkm


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, kmask=None, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False, bwd: str = "pallas"):
    """Blockwise flash attention over [B, T, H, D] (differentiable).

    Forward runs the Pallas kernel (never materialises [T, T]); the
    backward is a blockwise Pallas kernel pair too (dq grid over q-blocks,
    dk/dv grid over k-blocks) consuming the forward's logsumexp residual —
    ``bwd="xla"`` selects the rematerialising XLA fallback (the tests'
    correctness oracle). ``interpret=True`` runs the kernels in the Pallas
    interpreter (CPU tests). ``kmask`` [B, T]: key validity (1=real,
    0=padding) shared across heads — the padded/variable-length batch case;
    the kernel loads one [1, block_k] row slice per key block and ANDs it
    into the validity mask, so masked training keeps the flash memory
    envelope."""
    if bwd not in ("pallas", "xla"):
        raise ValueError(f"bwd must be 'pallas' or 'xla', got {bwd!r}")
    if kmask is not None:
        # float at the custom_vjp boundary (integer args would need float0
        # cotangents); the bwd returns zeros for it
        kmask = jnp.asarray(kmask, jnp.float32)
    return _flash(q, k, v, kmask, causal, block_q, block_k, interpret, bwd)


def flash_attention_block(q, k, v, *, kmask=None, q_offset: int = 0,
                          k_offset: int = 0,
                          causal: bool = False, block_q: int = 128,
                          block_k: int = 128, interpret: bool = False):
    """FORWARD-ONLY building block for chunked/ring attention: attention of
    q (absolute positions starting at ``q_offset``) over ONE k/v chunk
    (positions starting at ``k_offset``), returning
    ``(out, lse [B, H, T])`` — the per-row logsumexp needed to merge
    partial results across chunks with :func:`merge_attention_blocks`.

    Rows whose keys are entirely masked (causal, q < k_offset; or a fully
    kmasked chunk) return a ~-1e30 lse whose merge weight underflows to
    exactly 0 — but their ``out`` is mean(v), NOT 0 (every masked score
    equals the running-max sentinel, so p=1 uniformly). ``out`` alone is
    therefore meaningless without the lse weighting: always combine via
    merge_attention_blocks. ``kmask`` [B, Tk]: THIS key chunk's validity."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    bq, bk, q_pad, k_pad = _block_sizes2(Tq, Tk, block_q, block_k)
    qt = _pad_bh(q, q_pad)
    kt, vt = _pad_bh(k, k_pad), _pad_bh(v, k_pad)
    km = _pad_km(kmask, k_pad) if kmask is not None else None
    # t_real_k gates KEY validity (Tk, not Tq — the chunk may be shorter);
    # padded q rows emit garbage that is sliced off below
    out, lse = _fwd_pallas_call(
        qt, kt, vt, D=D, bq=bq, bk=bk, q_pad=q_pad, k_pad=k_pad, t_real_k=Tk,
        causal=causal, scale=scale, q_off=q_offset, k_off=k_offset,
        interpret=interpret, dtype=q.dtype, kmask=km, H=H)
    # fully masked rows: m stays _NEG_BIG so lse = m + log(l) is ~-1e30
    # and the merge weight underflows to 0 (their out is mean(v), see
    # docstring — only the weighted combination is meaningful)
    lse_b = lse[:, 0, :Tq].reshape(B, H, Tq)
    return _from_bh(out, B, Tq, H), lse_b


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_block_diff(q, k, v, kmask, q_offset, k_offset, causal, block_q,
                      block_k, interpret):
    return flash_attention_block(
        q, k, v, kmask=kmask, q_offset=q_offset, k_offset=k_offset,
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret)


def _flash_block_diff_fwd(q, k, v, kmask, q_offset, k_offset, causal,
                          block_q, block_k, interpret):
    out, lse = flash_attention_block(
        q, k, v, kmask=kmask, q_offset=q_offset, k_offset=k_offset,
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret)
    return (out, lse), (q, k, v, kmask, out, lse)


def _flash_block_diff_bwd(q_offset, k_offset, causal, block_q, block_k,
                          interpret, res, cts):
    """Backward with BOTH cotangents (do, dlse). d lse_i/d s_ij = p_ij, so
    the dlse contribution folds into the delta shift:
    ds = p * (do@v^T - delta + dlse)  =>  delta_eff = delta - dlse
    (FlashAttention-2 eq. dS extended for a differentiable logsumexp —
    exactly what chunk-merged/ring attention training needs)."""
    q, k, v, kmask, o, lse = res
    do, dlse = cts
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    bq, bk, q_pad, k_pad = _block_sizes2(Tq, Tk, block_q, block_k)
    qt, dot = _pad_bh(q, q_pad), _pad_bh(do, q_pad)
    kt, vt = _pad_bh(k, k_pad), _pad_bh(v, k_pad)
    km = _pad_km(kmask, k_pad) if kmask is not None else None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.swapaxes(delta, 1, 2) - dlse.astype(jnp.float32)  # [B,H,Tq]
    delta = _row_layout(delta, B, H, Tq, q_pad)
    lse_r = _row_layout(lse, B, H, Tq, q_pad)
    dq, dk, dv = _bwd_pallas_calls(
        qt, kt, vt, dot, lse_r, delta, D=D, bq=bq, bk=bk, q_pad=q_pad,
        k_pad=k_pad, t_real_q=Tq, t_real_k=Tk, causal=causal, scale=scale,
        q_off=q_offset, k_off=k_offset, interpret=interpret, dtype=q.dtype,
        kmask=km, H=H)
    dkm = jnp.zeros_like(kmask) if kmask is not None else None
    return (_from_bh(dq, B, Tq, H), _from_bh(dk, B, Tk, H),
            _from_bh(dv, B, Tk, H), dkm)


_flash_block_diff.defvjp(_flash_block_diff_fwd, _flash_block_diff_bwd)


def flash_attention_block_grad(q, k, v, *, kmask=None, q_offset: int = 0,
                               k_offset: int = 0, causal: bool = False,
                               block_q: int = 128, block_k: int = 128,
                               interpret: bool = False):
    """DIFFERENTIABLE chunked flash attention: like
    :func:`flash_attention_block` but (out, lse) both carry gradients —
    the merge (and anything downstream of it) backpropagates exactly
    through every chunk via blockwise Pallas kernels. This is the
    training-capable building block for chunk-sequential and ring
    attention schedules. ``kmask`` [B, Tk]: this key chunk's validity."""
    if kmask is not None:
        kmask = jnp.asarray(kmask, jnp.float32)
    return _flash_block_diff(q, k, v, kmask, q_offset, k_offset, causal,
                             block_q, block_k, interpret)


def merge_attention_blocks(parts):
    """Merge [(out_i [B,T,H,D], lse_i [B,H,T])] partial attentions over
    DISJOINT key chunks into the attention over their union:
    out = sum_i w_i * out_i with w_i = exp(lse_i - logsumexp_i(lse_i)).
    Streaming-softmax identity — exact up to float rounding."""
    outs = jnp.stack([o for o, _ in parts])                # [N, B, T, H, D]
    lses = jnp.stack([l for _, l in parts])                # [N, B, H, T]
    lse_tot = jax.nn.logsumexp(lses, axis=0)               # [B, H, T]
    w = jnp.exp(lses - lse_tot[None])                      # [N, B, H, T]
    w = jnp.moveaxis(w, 3, 2)[..., None]                   # [N, B, T, H, 1]
    return jnp.sum(outs.astype(jnp.float32) * w, axis=0).astype(outs.dtype)


# VMEM ceiling note: each grid program copies the full [t_pad, D] K and V
# (forward/dq kernels) or full q/do (dk/dv kernel) into VMEM (~4*T*D*bytes
# of the ~16MB/core budget — T up to ~32K at D=64 bf16). Beyond that,
# shard the sequence instead (ring attention, parallel/ring.py) — the
# ring's per-shard blocks land back under the ceiling. A second grid axis
# could lift this limit in-kernel; not needed at the lengths the framework
# targets single-chip.


# ---------------------------------------------------------------------------
# Decode-mode attention (KV-cache serving path, nn/decode.py)
# ---------------------------------------------------------------------------


def decode_attention(q, k, v, q_positions):
    """Attention of a short new-token chunk against a gathered KV cache.

    ``q`` [B, Tc, H, D] — the chunk being decoded/prefilled (Tc is 1 in
    steady-state decode, a prefill-chunk bucket otherwise); ``k``/``v``
    [B, K, H, D] — the cache span gathered for each row, laid out so index
    ``g`` along K IS absolute sequence position ``g`` (nn/decode.py writes
    the chunk's own k/v into the cache before gathering, so no separate
    self-attention term exists); ``q_positions`` [B, Tc] int32 — each
    query's absolute position. Causality is positional: key ``g`` is valid
    iff ``g <= q_positions[b, t]``, which simultaneously enforces the
    causal mask and hides every cache slot past the row's written length
    (unwritten pool pages hold finite garbage, masked to an exact-zero
    softmax weight).

    Deliberately plain XLA, not Pallas: flash attention exists to keep the
    [T, T] score tensor out of HBM, but here the score tensor is
    [B, H, Tc, K] with Tc <= one prefill chunk — a few hundred KB at
    serving shapes. The flash kernel remains the training/full-prefill
    path. Numerics mirror ``parallel/ring.py local_attention`` (scores in
    the operand dtype, -inf mask clamped at ``_NEG_BIG``) so a
    cache-backed prefill agrees with the full forward on the XLA path.

    Bit-exactness under padding (the serving tier's batched==unbatched
    guarantee): padded batch rows are independent (row-block computation),
    and padded/masked cache tail positions contribute exp(-1e30 - m) = 0
    exactly to the softmax and 0 * v to the value sum — trailing zero
    terms that leave every real row's reduction bitwise unchanged.
    """
    K = k.shape[1]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale      # [B, H, Tc, K]
    valid = jnp.arange(K)[None, None, None, :] <= \
        q_positions[:, None, :, None]                    # [B, 1, Tc, K]
    s = jnp.where(valid, s, -jnp.inf)
    # position 0 is always <= q_position, so no row is fully masked; the
    # clamp keeps the same guard local_attention carries regardless
    s = jnp.maximum(s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)           # [B, Tc, H, D]
