"""Flash attention — Pallas TPU kernel with online (streaming) softmax.

The XLA attention path (`parallel/ring.py local_attention`) materialises
the [B, H, T, T] score matrix in HBM; at long T that traffic dominates
(the framework's ResNet-style roofline analysis, docs/PERF.md, shows HBM
bandwidth is the binding resource on this chip). This kernel computes
attention blockwise in VMEM — scores never leave the chip — using the
standard streaming-softmax recurrence (running max m, normaliser l,
rescaled accumulator), one (batch*head, q-block) program per grid cell
looping over key blocks.

Beyond-reference scope: the reference (DL4J 0.9.2) has no attention layer
at all (SURVEY.md §5.7); this accelerates the framework's TransformerLM
extension. Training uses a custom VJP whose backward recomputes attention
with plain XLA ops from the saved q/k/v (rematerialisation — the forward
saves no [T, T] intermediates, so the backward rebuilds them; exact
gradients of the same math).

CPU/tests: ``interpret=True`` runs the identical kernel in the Pallas
interpreter; the layer's default ("auto") uses the kernel only on TPU and
falls back to the XLA path elsewhere and for masked (kmask) variants.
Attention dropout is applied to the attention OUTPUT (not the probability
matrix) in both paths — see MultiHeadAttention.apply in
nn/layers/attention.py — so dropout is flash-compatible and does not gate
the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_BIG = -1e30


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
            t_real: int, t_pad: int, causal: bool, scale: float):
    """One q-block vs all key blocks. Refs: q [1, block_q, D];
    k/v [1, t_pad, D]; o [1, block_q, D]."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                     # [bq, D]
    d = q.shape[-1]
    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)                              # [bq, 1]

    m0 = jnp.full((block_q, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        k_pos = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)                          # [1, bk]
        valid = k_pos < t_real
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        s = jnp.where(valid, s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                                   # [bq, bk]
        alpha = jnp.exp(m - m_new)                               # [bq, 1]
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    n_kb = t_pad // block_k
    if causal:
        # key blocks strictly above the diagonal contribute nothing:
        # stop after the block containing this q-block's last position
        n_kb = jnp.minimum(n_kb, (qi + 1) * block_q // block_k
                           + (1 if block_q % block_k else 0))
    m, l, acc = lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_raw(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    """q/k/v: [B, T, H, D] -> [B, T, H, D]. Forward only."""
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    bq = min(block_q, max(T, 1))
    bk = min(block_k, max(T, 1))
    t_pad = _cdiv(T, bq) * bq
    t_pad = _cdiv(t_pad, bk) * bk

    def to_bh(x):
        x = jnp.swapaxes(x, 1, 2).reshape(B * H, T, D)
        if t_pad != T:
            x = jnp.pad(x, ((0, 0), (0, t_pad - T), (0, 0)))
        return x

    qt, kt, vt = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, t_pad // bq)
    kernel = functools.partial(
        _kernel, block_q=bq, block_k=bk, t_real=T, t_pad=t_pad,
        causal=causal, scale=scale)
    kw = {}
    if _VMEM is not None and not interpret:
        kw["memory_space"] = _VMEM
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0), **kw),
            pl.BlockSpec((1, t_pad, D), lambda bh, qi: (bh, 0, 0), **kw),
            pl.BlockSpec((1, t_pad, D), lambda bh, qi: (bh, 0, 0), **kw),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0), **kw),
        out_shape=jax.ShapeDtypeStruct((B * H, t_pad, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :T].reshape(B, H, T, D)
    return jnp.swapaxes(out, 1, 2)


def _reference(q, k, v, causal: bool):
    """The same math in plain XLA ops — used by the equivalence tests."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk",
                   q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[1]
        msk = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(msk[None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _reference_chunked(q, k, v, causal: bool, chunk: int = 128):
    """Attention computed q-chunk-at-a-time with ``lax.map`` — identical
    math to :func:`_reference`, but only [B, H, chunk, T] scores exist at
    once. The custom VJP differentiates THIS function, so the backward is
    memory-bounded too (vjp of lax.map is a scan with per-chunk residuals)
    and training works at the long T the flash forward enables."""
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    n = _cdiv(T, chunk)
    t_pad = n * chunk
    qp = jnp.pad(q, ((0, 0), (0, t_pad - T), (0, 0), (0, 0)))
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    k_pos = jnp.arange(T)

    def one_chunk(ci):
        qc = lax.dynamic_slice_in_dim(qp, ci * chunk, chunk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qc.astype(jnp.float32), kf) * scale
        q_pos = ci * chunk + jnp.arange(chunk)
        valid = jnp.ones((chunk, T), bool)
        if causal:
            valid = k_pos[None, :] <= q_pos[:, None]
        s = jnp.where(valid[None, None], s, _NEG_BIG)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vf)        # [B,chunk,H,D]

    out = lax.map(one_chunk, jnp.arange(n))                # [n,B,chunk,H,D]
    out = jnp.moveaxis(out, 0, 1).reshape(B, t_pad, H, D)
    return out[:, :T].astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_raw(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_raw(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    # Rematerialise for the backward. Chunking is a memory/throughput
    # trade: lax.map serialises chunks (~15% slower at T=2048), so use the
    # dense [T,T] recompute while the f32 score tensor is affordable and
    # switch to q-chunks only when it is not (without this, long-T training
    # dies exactly like the XLA path the forward kernel replaces).
    q, k, v = res
    B, T, H, _ = q.shape
    score_bytes = 4 * B * H * T * T
    # the dense vjp holds ~3 score-sized f32 tensors at once (softmax
    # residual p + dp/ds temporaries), so budget for 3x, not 1x
    if 3 * score_bytes <= 4 << 30:
        fn = lambda q_, k_, v_: _reference(q_, k_, v_, causal)
    else:
        fn = lambda q_, k_, v_: _reference_chunked(q_, k_, v_, causal)
    _, vjp = jax.vjp(fn, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Blockwise flash attention over [B, T, H, D] (differentiable).

    Forward runs the Pallas kernel (never materialises [T, T]); backward
    recomputes with XLA ops from q/k/v. ``interpret=True`` runs the kernel
    in the Pallas interpreter (CPU tests)."""
    return _flash(q, k, v, causal, block_q, block_k, interpret)


# VMEM ceiling note: each grid program copies the full [t_pad, D] K and V
# into VMEM (~4*T*D*bytes of the ~16MB/core budget — T up to ~32K at
# D=64 bf16). Beyond that, shard the sequence instead (ring attention,
# parallel/ring.py) — the ring's per-shard blocks land back under the
# ceiling. A k-block grid axis could lift this limit in-kernel; not needed
# at the lengths the framework targets single-chip.
