"""Active-mesh context.

Layers that can exploit mesh axes (ring attention over ``seq``, expert
dispatch over ``model``) look the mesh up here instead of threading it
through every ``apply`` signature. ``use_mesh`` is re-entrant and
trace-safe: it only sets a module-level variable read at trace time.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from jax.sharding import Mesh

_ACTIVE_MESH: Optional[Mesh] = None


def current_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]):
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev
