"""Pipeline parallelism over a ``pipe`` mesh axis (GPipe schedule, SPMD).

Beyond-reference capability (the reference is data-parallel only,
SURVEY.md §2.5). Every device holds ONE stage's parameters (the stacked
per-stage pytree is sharded on its leading axis over ``pipe``); microbatches
flow through the ring: at step t each device applies its stage to the
activation it holds and ``ppermute``s the result to the next device. After
``n_micro + n_stages - 1`` steps the last device has produced every
microbatch's output. The whole schedule lives inside one jit/shard_map
program, so backward is just autodiff (the transpose of ppermute is the
reverse ppermute — XLA schedules the bubble-filling automatically).

Constraint: inter-stage activations share one shape (classic GPipe layout —
stages are "blocks of equal width"); stage 0 maps input→hidden internally if
needed via its own parameters.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.ring import shard_map


def _gpipe_shard(params_local, x_micro, *, stage_apply, axis_name, n_stages,
                 aux_width=None, aux_combine=None):
    """Runs on each pipe rank. params_local: this rank's stage params (leading
    stage axis already stripped to size 1 by shard_map → squeezed here).
    x_micro: [M, mb, ...] microbatched input (replicated across pipe).
    ``stage_apply(params, x, micro)`` is one stage's forward for microbatch
    index ``micro`` (clamped during bubble steps, whose results are
    discarded); with ``aux_width`` set it returns ``(out, aux[aux_width])``
    and this function returns ``(outs, auxs [1, M, aux_width])`` — each
    rank's per-microbatch auxiliary emissions (e.g. BatchNorm batch stats),
    optionally passed through ``aux_combine`` (e.g. a data-axis pmean).
    Returns [M, mb, ...] outputs (valid on the LAST rank, zeros elsewhere;
    psum-broadcast so every rank returns them)."""
    params_local = jax.tree_util.tree_map(lambda a: a[0], params_local)
    idx = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    total = M + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    with_aux = aux_width is not None

    def body(t, carry):
        buf, outs, auxs = carry
        micro = jnp.clip(t - idx, 0, M - 1)
        inp = jnp.where(idx == 0, x_micro[jnp.minimum(t, M - 1)], buf)
        res = stage_apply(params_local, inp, micro)
        out, aux = res if with_aux else (res, None)
        shifted = lax.ppermute(out, axis_name, perm)
        # Last rank commits microbatch t-(S-1); earlier (wrapped) writes are
        # overwritten by the later, correct ones.
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(idx == n_stages - 1, out, jnp.zeros_like(out)),
            (t - (n_stages - 1)) % M, 0,
        )
        if with_aux:
            if aux_combine is not None:
                aux = aux_combine(aux)
            # this rank's aux for micro t-idx is valid iff idx <= t < idx+M;
            # late bubble steps would otherwise overwrite earlier valid rows
            # (slot (t-idx) % M wraps)
            slot = (t - idx) % M
            valid = jnp.logical_and(t >= idx, t - idx < M)
            prev = lax.dynamic_index_in_dim(auxs, slot, 0, keepdims=False)
            auxs = lax.dynamic_update_index_in_dim(
                auxs, jnp.where(valid, aux, prev), slot, 0)
        return shifted, outs, auxs

    # carries must be typed as device-varying over the pipe axis from the
    # start (they become varying after the first ppermute/update)
    def _pvary(x):
        try:
            return lax.pcast(x, axis_name, to="varying")
        except ValueError:  # already varying
            return x
        except (AttributeError, TypeError):
            pass
        try:
            return lax.pvary(x, axis_name)  # jax ~0.5/0.6 spelling
        except AttributeError:
            # jax 0.4.x: avals carry no varying-axis type, so there is
            # nothing to cast — the carry is usable as-is
            return x

    buf = _pvary(jnp.zeros_like(x_micro[0]))
    outs = _pvary(jnp.zeros_like(x_micro))
    auxs = _pvary(jnp.zeros((M, aux_width if with_aux else 1), jnp.float32))
    buf, outs, auxs = lax.fori_loop(0, total, body, (buf, outs, auxs),
                                    unroll=True)
    # Only the last rank holds real outputs (zeros elsewhere): psum over the
    # pipe ring broadcasts them so the result is replicated across stages.
    outs = lax.psum(outs, axis_name)
    return (outs, auxs[None]) if with_aux else outs


class PipelineParallel:
    """GPipe training driver.

    ``stage_apply(stage_params, x) -> y`` is one stage's forward;
    ``stacked_params`` holds every stage stacked on axis 0.
    ``loss_fn(y, labels) -> scalar`` scores the final stage's output.

    The train step shards microbatches over ``data`` and stages over
    ``pipe`` in ONE compiled program.
    """

    def __init__(
        self,
        stage_apply: Callable,
        n_stages: int,
        mesh: Mesh,
        *,
        loss_fn: Callable,
        data_axis: str = "data",
        pipe_axis: str = "pipe",
        learning_rate: float = 1e-2,
    ):
        if n_stages != mesh.shape[pipe_axis]:
            raise ValueError(
                f"n_stages={n_stages} must equal the mesh's '{pipe_axis}' axis "
                f"size ({mesh.shape[pipe_axis]}): one stage per pipe rank"
            )
        self.stage_apply = stage_apply
        self.n_stages = n_stages
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.data_axis = data_axis
        self.pipe_axis = pipe_axis
        self.lr = learning_rate
        self._step = None

    def forward(self, stacked_params, x_micro):
        """Pipelined forward; returns [M, mb, ...] outputs (from last stage)."""
        fn = functools.partial(
            _gpipe_shard,
            stage_apply=lambda p, x, _micro: self.stage_apply(p, x),
            axis_name=self.pipe_axis,
            n_stages=self.n_stages,
        )
        pspec = jax.tree_util.tree_map(lambda _: P(self.pipe_axis), stacked_params)
        xspec = P(None, self.data_axis)
        out = shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(pspec, xspec),
            out_specs=xspec,
        )(stacked_params, x_micro)
        return out

    def _loss(self, stacked_params, x_micro, y_micro):
        out = self.forward(stacked_params, x_micro)
        # outputs are zero except on the last pipe rank's shard-view; after
        # shard_map they're the assembled global array, so loss is direct
        return self.loss_fn(out, y_micro)

    def make_train_step(self):
        @jax.jit
        def step(stacked_params, x_micro, y_micro):
            loss, grads = jax.value_and_grad(self._loss)(stacked_params, x_micro, y_micro)
            new_params = jax.tree_util.tree_map(lambda p, g: p - self.lr * g, stacked_params, grads)
            return new_params, loss

        return step

    def fit_batch(self, stacked_params, x, y, n_micro: int):
        """Split [B,...] into n_micro microbatches, run one pipelined step."""
        if self._step is None:
            self._step = self.make_train_step()
        B = x.shape[0]
        assert B % n_micro == 0, "batch must divide into microbatches"
        xm = x.reshape(n_micro, B // n_micro, *x.shape[1:])
        ym = y.reshape(n_micro, B // n_micro, *y.shape[1:])
        return self._step(stacked_params, xm, ym)


def stack_stage_params(per_stage: Sequence[Any]):
    """Stack per-stage param pytrees on a new leading ``pipe`` axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)
