"""Explicit data-parallel gradient exchange: compressed collectives and
cross-replica sharded weight updates.

The default ParallelWrapper path feeds a globally-sharded batch to the
single-chip jitted step and lets XLA insert a dense gradient all-reduce with
the optimizer update replicated on every chip. This module is the explicit
alternative — a ``shard_map`` over the ``data`` mesh axis wrapping the SAME
step body (``nn/model.py`` / ``nn/graph.py`` expose a ``grad_exchange=``
hook) — enabling two reference-capability optimizations the implicit path
cannot express:

1. **Threshold compression** (DL4J SharedTrainingMaster / ND4J
   thresholdEncode parity, ``parallel/compress.py``): each replica ternary-
   quantizes its local gradient against a threshold, carries the remainder in
   a per-replica residual (error feedback), and replicas exchange the 2-bit
   packed encodings by all-gather — 16x fewer wire bytes than a dense f32
   all-reduce. The residual rides in the DONATED step carry (tupled with the
   optimizer state), so compression stays inside the one compiled executable.

2. **Cross-replica sharded weight update** ("Automatic Cross-Replica
   Sharding of Weight Update in Data-Parallel Training", PAPERS.md):
   gradients are reduce-scattered instead of all-reduced, each replica
   applies the optimizer update to its 1/R shard only (optimizer state lives
   sharded over ``data`` as ``[R, m]`` stacks of flat shards), and updated
   params are all-gathered. The redundant R-way replicated update becomes
   1/R of the math and memory.

Both are off by default (``docs/PERF.md``): on a single ICI-connected slice
the dense fused psum is already near-optimal; these switches matter when the
exchange crosses DCN (multi-slice / multi-host pods) or optimizer state
dominates HBM.

Per-layer plan: a layer/vertex is exchanged flat (modes above) only when its
gradient leaves share one floating dtype and it declares no gradient
normalization (gn needs the full global gradient); otherwise it falls back
to an exact per-leaf ``pmean`` + replicated update inside the same step.
Everything is deterministic: fixed-order reductions, no host round-trips.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.analysis import retrace_guard
from deeplearning4j_tpu.parallel import compress as compression
from deeplearning4j_tpu.train.updaters import apply_gradient_normalization
from deeplearning4j_tpu.utils import bucketing

__all__ = ["DataParallelStep", "GradExchange"]


# ---------------------------------------------------------------------------
# Per-layer exchange plan
# ---------------------------------------------------------------------------


@dataclass
class _Entry:
    """Static exchange metadata for one layer/vertex (captured by the traced
    closures; every field is a python constant, so it never retraces)."""

    key: Any
    treedef: Any                      # params-entry pytree structure
    shapes: Tuple[Tuple[int, ...], ...]
    n: int                            # total elements across leaves
    m: int                            # per-replica shard length
    n_pad: int                        # R * m
    dtype: Any                        # uniform leaf dtype (flat modes)
    mode: str                         # "sharded" | "dense"
    compress: bool
    updater: Any
    cfg: Any                          # layer/vertex config (gn + constraints)


def _flat(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) == 1:
        return leaves[0].reshape(-1)
    return jnp.concatenate([l.reshape(-1) for l in leaves])


def _pad_flat(flat, n_pad: int):
    n = flat.shape[0]
    if n_pad == n:
        return flat
    return jnp.concatenate([flat, jnp.zeros((n_pad - n,), flat.dtype)])


def _unflat(flat, entry: _Entry):
    out, off = [], 0
    for shp in entry.shapes:
        k = int(np.prod(shp)) if shp else 1
        out.append(flat[off:off + k].reshape(shp))
        off += k
    return jax.tree_util.tree_unflatten(entry.treedef, out)


def _apply_entry_constraints(cfg, p_new):
    if getattr(cfg, "constraints", None):
        from deeplearning4j_tpu.nn.constraints import apply_constraints

        p_new = apply_constraints(cfg, p_new)
    return p_new


# ---------------------------------------------------------------------------
# The exchange (runs INSIDE the shard_map-traced step body)
# ---------------------------------------------------------------------------


class GradExchange:
    """Collective gradient exchange + parameter update for one model.

    Instances are handed to the step factories (``_step_body(...,
    grad_exchange=...)``); every method below executes inside the shard_map
    trace, where arrays are the per-replica LOCAL views and collectives over
    ``axis`` are explicit.
    """

    def __init__(self, entries: Dict[Any, _Entry], order, container: str,
                 axis: str, n_shards: int, threshold: float):
        self.entries = entries
        self.order = list(order)
        self.container = container            # "tuple" (MLN) | "dict" (CG)
        self.axis = axis
        self.n_shards = n_shards
        self.threshold = float(threshold)

    # -- replica-mean of the scalar loss and the mutable layer state -------
    def mean_loss(self, loss):
        return lax.pmean(loss, self.axis)

    def mean_state(self, state):
        """Average batch-derived layer state (BatchNorm running stats) over
        replicas; non-float leaves (counters, ()) pass through untouched."""

        def avg(a):
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
                return lax.pmean(a, self.axis)
            return a

        return jax.tree_util.tree_map(avg, state)

    # -- per-entry update ---------------------------------------------------
    def _dense_entry(self, e: _Entry, g, p, o, it):
        """Exact fallback: per-leaf pmean, gradient normalization on the
        global gradient, replicated structured update — bit-for-bit the
        implicit path's math, minus XLA's fusion freedom."""
        g = jax.tree_util.tree_map(lambda a: lax.pmean(a, self.axis), g)
        gn = getattr(e.cfg, "gradient_normalization", None)
        if gn:
            g = apply_gradient_normalization(
                gn, getattr(e.cfg, "gradient_normalization_threshold", 1.0), g)
        upd, o_new = e.updater.update(g, o, p, it)
        p_new = jax.tree_util.tree_map(lambda a, d: a - d, p, upd)
        return _apply_entry_constraints(e.cfg, p_new), o_new

    def _flat_entry(self, e: _Entry, g, p, o, r_loc, it):
        """Flat exchange: compressed and/or shard-updated."""
        thr = self.threshold
        R = self.n_shards
        g_mean_full = None
        r_new = r_loc
        if e.compress:
            # residual + encode run in f32 regardless of the param dtype so
            # sub-threshold error feedback never rounds away in bf16
            with obs.span("phase.compress", mode="trace"):
                gflat32 = _pad_flat(_flat(g).astype(jnp.float32), e.n_pad)
                packed, r = compression.encode_packed(
                    gflat32, r_loc.reshape(-1), thr)
                gathered = lax.all_gather(packed, self.axis)   # [R, nbytes]
                g_mean_full = compression.decode_gathered(
                    gathered, e.n_pad, thr, jnp.float32) / R
                r_new = r[None]                                # local [1, n_pad]
        if e.mode == "sharded":
            idx = lax.axis_index(self.axis)
            if e.compress:
                g_shard = lax.dynamic_slice(
                    g_mean_full, (idx * e.m,), (e.m,)).astype(e.dtype)
            else:
                g_shard = lax.psum_scatter(
                    _pad_flat(_flat(g), e.n_pad), self.axis,
                    scatter_dimension=0, tiled=True) / R
            p_flat = _pad_flat(_flat(p), e.n_pad)
            p_shard = lax.dynamic_slice(p_flat, (idx * e.m,), (e.m,))
            o_loc = jax.tree_util.tree_map(lambda a: a[0], o)  # [1,m] -> [m]
            upd, o_new_loc = e.updater.update(g_shard, o_loc, p_shard, it)
            p_new_flat = lax.all_gather(
                p_shard - upd, self.axis, tiled=True)          # [n_pad]
            o_new = jax.tree_util.tree_map(lambda a: a[None], o_new_loc)
            p_new = _unflat(p_new_flat[:e.n], e)
        else:
            # compressed, replicated update: every replica decodes the same
            # fixed-order sum, so the updates are identical without any
            # further collective
            g_tree = _unflat(g_mean_full[:e.n].astype(e.dtype), e)
            upd, o_new = e.updater.update(g_tree, o, p, it)
            p_new = jax.tree_util.tree_map(lambda a, d: a - d, p, upd)
        return _apply_entry_constraints(e.cfg, p_new), o_new, r_new

    # -- whole-model update -------------------------------------------------
    def update(self, grads, params, opt_state, residuals, it):
        """Replaces the step body's per-layer update loop. Returns
        ``(new_params, new_opt, new_residuals)`` in the model's container
        type (tuple of layers / dict of vertices)."""
        # trace-time span: this whole method runs inside the shard_map trace,
        # so a runtime span here would time tracing, not the collectives —
        # mode="trace" records exactly that (compile-cost attribution)
        with obs.span("phase.exchange", mode="trace"):
            return self._update_traced(grads, params, opt_state, residuals, it)

    def _update_traced(self, grads, params, opt_state, residuals, it):
        new_p: Dict[Any, Any] = {}
        new_o: Dict[Any, Any] = {}
        new_r: Dict[Any, Any] = {}
        for key in self.order:
            e = self.entries.get(key)
            g = grads[key]
            if e is None or not jax.tree_util.tree_leaves(g):
                new_p[key] = params[key]
                new_o[key] = opt_state[key]
                new_r[key] = residuals[key]
                continue
            if e.mode == "dense":
                new_p[key], new_o[key] = self._dense_entry(
                    e, g, params[key], opt_state[key], it)
                new_r[key] = residuals[key]
            else:
                new_p[key], new_o[key], new_r[key] = self._flat_entry(
                    e, g, params[key], opt_state[key], residuals[key], it)
        if self.container == "tuple":
            keys = self.order
            return (tuple(new_p[k] for k in keys),
                    tuple(new_o[k] for k in keys),
                    tuple(new_r[k] for k in keys))
        return new_p, new_o, new_r


# ---------------------------------------------------------------------------
# Host-side runner
# ---------------------------------------------------------------------------


class DataParallelStep:
    """Explicit-exchange train-step runner for ParallelWrapper.

    Wraps the model's step body in ``shard_map`` over the mesh's ``data``
    axis and jits the result with params/opt-carry/state donated — one
    compiled executable per batch bucket, same as the single-chip path. The
    optimizer carry is ``(opt_state, residuals)``: sharded-mode entries hold
    flat ``[R, m]`` optimizer stats placed with ``P("data")`` (each replica
    owns one row), compressed entries additionally carry an f32 ``[R, n_pad]``
    error-feedback residual. ``begin()`` converts the model's structured
    optimizer state into this layout; ``finish()`` converts it back, so
    outside an active fit the model stays serializable/usable as usual.
    Residuals persist across ``begin``/``finish`` — dropping them would lose
    pending sub-threshold gradient mass.
    """

    COMM_SITE = "dp.grads"

    def __init__(self, model, mesh, *, compress: bool = False,
                 sharded_update: bool = False, threshold: float = 1e-3):
        if jax.process_count() > 1:
            raise NotImplementedError(
                "DataParallelStep supports single-process meshes only; "
                "for multi-process data parallelism use the elastic runtime "
                "(train/elastic.py ElasticTrainer over parallel/elastic.py "
                "membership), which shards the optimizer update and "
                "compresses payloads across hosts")
        if model.params is None:
            model.init()
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        self.model = model
        self.mesh = mesh
        self.is_graph = isinstance(model, ComputationGraph)
        self.R = mesh.shape["data"]
        self.compress = bool(compress)
        self.sharded_update = bool(sharded_update)
        self.threshold = float(threshold)
        self._sharded = NamedSharding(mesh, P("data"))
        self._repl = NamedSharding(mesh, P())
        self._build_plan()
        self.exchange = GradExchange(
            self._entries, self._order,
            "dict" if self.is_graph else "tuple",
            "data", self.R, self.threshold)
        self._step = self._build_step()
        self._opt_flat = None
        self._residual = None
        self._active = False
        self._record_comm()
        # checkpoint/resume integration (train/resilience.py): the save path
        # finds the active runner here to snapshot flat opt state + residuals
        model._dp_runner = self

    # -- plan ---------------------------------------------------------------
    def _build_plan(self):
        model = self.model
        if self.is_graph:
            order = list(model.topo_order)
            updaters = model._updaters
            cfg_of = {k: model.rt[k].config for k in order}
            params_of = model.params
        else:
            order = list(range(len(model.layers)))
            updaters = {i: u for i, u in enumerate(model._updaters)}
            cfg_of = {i: l for i, l in enumerate(model.layers)}
            params_of = {i: p for i, p in enumerate(model.params)}
        entries: Dict[Any, _Entry] = {}
        for key in order:
            p = params_of[key]
            leaves, treedef = jax.tree_util.tree_flatten(p)
            if not leaves:
                continue
            cfg = cfg_of[key]
            n = sum(int(np.prod(l.shape)) for l in leaves)
            dtypes = {jnp.dtype(l.dtype) for l in leaves}
            uniform_float = (len(dtypes) == 1 and
                             jnp.issubdtype(next(iter(dtypes)), jnp.floating))
            gn = getattr(cfg, "gradient_normalization", None)
            eligible = uniform_float and not gn
            if eligible and self.sharded_update:
                mode = "sharded"
            elif eligible and self.compress:
                mode = "replicated"     # compressed exchange, replicated update
            else:
                mode = "dense"          # exact pmean fallback (gn, mixed dtypes)
            m = -(-n // self.R)
            entries[key] = _Entry(
                key=key, treedef=treedef,
                shapes=tuple(tuple(l.shape) for l in leaves),
                n=n, m=m, n_pad=m * self.R,
                dtype=(next(iter(dtypes)) if uniform_float else None),
                mode=mode, compress=(self.compress and eligible),
                updater=updaters[key], cfg=cfg)
        self._entries = entries
        self._order = order

    def comm_stats(self) -> dict:
        """Static per-step byte accounting for the gradient exchange.

        ``dense_bytes``: what a dense all-reduce of every exchanged gradient
        would move (per replica, payload bytes). ``wire_bytes``: what THIS
        configuration moves for gradients. ``param_bytes``: the updated-param
        all-gather added by sharded mode — reported separately so compression
        ratios stay honest about the extra parameter traffic."""
        dense = wire = param = 0
        for e in self._entries.values():
            itemsize = jnp.dtype(e.dtype).itemsize if e.dtype is not None else 4
            nbytes = e.n * itemsize
            dense += nbytes
            if e.compress:
                wire += compression.packed_nbytes(e.n_pad)
            else:
                wire += nbytes
            if e.mode == "sharded":
                param += nbytes
        return {"dense_bytes": dense, "wire_bytes": wire,
                "param_bytes": param,
                "n_entries": len(self._entries),
                "compressed_entries": sum(e.compress
                                          for e in self._entries.values()),
                "sharded_entries": sum(e.mode == "sharded"
                                       for e in self._entries.values())}

    def _record_comm(self):
        s = self.comm_stats()
        bucketing.telemetry().record_comm(
            self.COMM_SITE, s["dense_bytes"], s["wire_bytes"],
            s["param_bytes"])

    # -- step construction --------------------------------------------------
    def _opt_spec(self, e: Optional[_Entry]):
        return P("data") if (e is not None and e.mode == "sharded") else P()

    def _build_step(self):
        if self.is_graph:
            body = self.model._make_step_body(False, grad_exchange=self.exchange)
        else:
            body = self.model._step_body(False, grad_exchange=self.exchange)

        def call(params, opt_carry, state, it, rng, a, b, fm, lm, carries, ew):
            return body(params, opt_carry, state, it, rng, a, b, fm, lm,
                        carries, ex_weight=ew)

        specs = [self._opt_spec(self._entries.get(k)) for k in self._order]
        if self.is_graph:
            opt_spec: Any = dict(zip(self._order, specs))
        else:
            opt_spec = tuple(specs)
        dp, repl = P("data"), P()
        in_specs = (repl, (opt_spec, dp), repl, repl, repl,
                    dp, dp, dp, dp, repl, dp)
        out_specs = (repl, (opt_spec, dp), repl, repl, repl)
        from deeplearning4j_tpu.nn.step_program import StepProgram

        # the grad-exchange step is its own AOT site: the compressed/sharded
        # exchange traces a different executable than the single-chip step,
        # and warmup (aot.warm_dp) / bundle restore must target it. NOT
        # registered under the model's step sites — rebuild_step()/reload()
        # call here again and replace the wrapper wholesale. The guard still
        # watches the model's step site (traces fire inside the body) against
        # dp.fit bucket traffic, +1 for the exchange's own executable.
        return StepProgram(
            call, "dp.step", model=self.model,
            wrap_body=lambda b: shard_map(
                b, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_rep=False),
            guard_site="cg.step" if self.is_graph else "mln.step",
            hits_site="dp.fit", extra_allowed=1)

    # -- optimizer-state layout conversion ----------------------------------
    def _to_flat_opt(self, e: _Entry, structured):
        """Structured per-layer opt state -> flat ``[R, m]`` stats, sharded
        over ``data``. Updater states are built leaf-parallel to the params
        (``_zeros_like_tree``), so ``tree_leaves`` yields outer-stat-major
        groups of ``len(e.shapes)`` leaves each, concatenated in the same
        order ``_flat`` uses for params/grads."""
        leaves = jax.tree_util.tree_leaves(structured)
        n_inner = len(e.shapes)
        if leaves and len(leaves) % n_inner != 0:
            raise ValueError(
                f"opt state for {e.key} has {len(leaves)} leaves, not a "
                f"multiple of the {n_inner} param leaves — cannot flatten")
        stats = []
        for i in range(0, len(leaves), n_inner):
            chunk = leaves[i:i + n_inner]
            flat = _pad_flat(
                jnp.concatenate([jnp.ravel(l) for l in chunk])
                if len(chunk) > 1 else jnp.ravel(chunk[0]), e.n_pad)
            stats.append(jax.device_put(
                flat.reshape(self.R, e.m), self._sharded))
        template = e.updater.init(jnp.zeros((e.n_pad,), e.dtype))
        tdef = jax.tree_util.tree_structure(template)
        return jax.tree_util.tree_unflatten(tdef, stats)

    def _from_flat_opt(self, e: _Entry, flat_entry):
        """Inverse of ``_to_flat_opt``: rebuild the structured, replicated
        per-layer opt state from the ``[R, m]`` stats."""
        leaves = jax.tree_util.tree_leaves(flat_entry)
        subtrees = []
        for leaf in leaves:
            flat = jax.device_put(leaf, self._repl).reshape(-1)[:e.n]
            subtrees.append(_unflat(flat, e))
        template = e.updater.init(jnp.zeros((e.n_pad,), e.dtype))
        tdef = jax.tree_util.tree_structure(template)
        return jax.tree_util.tree_unflatten(tdef, subtrees)

    def _init_residual(self):
        res: Dict[Any, Any] = {}
        for key in self._order:
            e = self._entries.get(key)
            if e is not None and e.compress:
                res[key] = jax.device_put(
                    jnp.zeros((self.R, e.n_pad), jnp.float32), self._sharded)
            else:
                res[key] = None
        if self.is_graph:
            return res
        return tuple(res[k] for k in self._order)

    def begin(self):
        """Enter exchange layout: build the donated opt carry from the
        model's (replicated) structured optimizer state."""
        if self._active:
            return
        model = self.model
        opt: Dict[Any, Any] = {}
        for key in self._order:
            e = self._entries.get(key)
            structured = model.opt_state[key]
            if e is not None and e.mode == "sharded":
                opt[key] = self._to_flat_opt(e, structured)
            else:
                opt[key] = jax.device_put(structured, self._repl)
        self._opt_flat = (opt if self.is_graph
                          else tuple(opt[k] for k in self._order))
        if self._residual is None:
            self._residual = self._init_residual()
        # Barrier before the carry enters the donated step chain: begin() runs
        # once per fit, and a restored model's opt leaves are fresh transfers.
        jax.block_until_ready(self._opt_flat)  # graftlint: disable=host-sync
        self._active = True

    def finish(self):
        """Leave exchange layout: write the structured optimizer state back
        onto the model (residuals stay on the runner)."""
        if not self._active:
            return
        model = self.model
        flat = self._opt_flat
        out: Dict[Any, Any] = {}
        for i, key in enumerate(self._order):
            e = self._entries.get(key)
            entry = flat[key] if self.is_graph else flat[i]
            if e is not None and e.mode == "sharded":
                out[key] = self._from_flat_opt(e, entry)
            else:
                out[key] = entry
        model.opt_state = (out if self.is_graph
                           else tuple(out[k] for k in self._order))
        self._opt_flat = None
        self._active = False

    # -- checkpoint/resume integration (train/resilience.py) -----------------
    def snapshot_opt_state(self):
        """The model-structured optimizer state as of NOW, without leaving
        the exchange layout (``finish`` logic, non-mutating) — what a
        checkpoint taken mid-fit must record."""
        if not self._active:
            return self.model.opt_state
        flat = self._opt_flat
        out: Dict[Any, Any] = {}
        for i, key in enumerate(self._order):
            e = self._entries.get(key)
            entry = flat[key] if self.is_graph else flat[i]
            if e is not None and e.mode == "sharded":
                out[key] = self._from_flat_opt(e, entry)
            else:
                out[key] = entry
        return out if self.is_graph else tuple(out[k] for k in self._order)

    def export_residuals(self) -> Dict[str, np.ndarray]:
        """Host copies of the per-replica error-feedback residuals, keyed by
        ``str(entry key)`` (npz-compatible). Empty when nothing compresses."""
        if self._residual is None:
            return {}
        res = (self._residual if self.is_graph
               else dict(zip(self._order, self._residual)))
        return {str(k): np.asarray(v)  # graftlint: disable=host-sync
                for k, v in res.items() if v is not None}

    def load_residuals(self, arrays: Dict[str, np.ndarray]):
        """Re-seed the ``[R, n_pad]`` residuals from a checkpoint's host
        arrays (inverse of ``export_residuals``). Entries absent from
        ``arrays`` stay zero — dropping them would silently lose pending
        sub-threshold gradient mass, so restore runs this before fitting."""
        res: Dict[Any, Any] = {}
        for key in self._order:
            e = self._entries.get(key)
            if e is None or not e.compress:
                res[key] = None
                continue
            a = arrays.get(str(key))
            if a is None:
                res[key] = jax.device_put(
                    jnp.zeros((self.R, e.n_pad), jnp.float32), self._sharded)
            else:
                res[key] = jax.device_put(
                    jnp.asarray(a, jnp.float32).reshape(self.R, e.n_pad),
                    self._sharded)
        self._residual = res if self.is_graph else tuple(
            res[k] for k in self._order)
        # Barrier: these H2D transfers feed a donated carry; materialize them
        # before the first step can reuse the buffers (async dispatch race).
        jax.block_until_ready(self._residual)  # graftlint: disable=host-sync

    def rebuild_step(self):
        """Re-trace the step (the model's divergence-guard config is baked
        into the traced body — see model.set_divergence_guard)."""
        self._step = self._build_step()

    def reload(self):
        """Re-enter the exchange layout around externally reloaded model
        state (divergence-guard rollback: params/opt restored from a
        checkpoint, updaters rebuilt with a backed-off LR). Rebuilds the
        plan/step so the new updater objects are the ones traced, then
        re-seeds residuals from the checkpoint when it carried any."""
        self._active = False
        self._opt_flat = None
        self._build_plan()
        self.exchange = GradExchange(
            self._entries, self._order,
            "dict" if self.is_graph else "tuple",
            "data", self.R, self.threshold)
        self._step = self._build_step()
        self.begin()
        pending = getattr(self.model, "_pending_residuals", None)
        if pending:
            self.load_residuals(pending)
            self.model._pending_residuals = None

    # -- dispatch -----------------------------------------------------------
    def fit_batch(self, x, y, fm, lm, ew=None):
        """MultiLayerNetwork step (mirrors ``model._fit_batch``)."""
        from deeplearning4j_tpu.nn.model import _cast_input, _cast_labels
        from deeplearning4j_tpu.train import resilience

        if not self._active:
            self.begin()
        model = self.model
        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_preempt(model.iteration)
            chaos.maybe_slow(model.iteration)
            x = chaos.maybe_nan_batch(model.iteration, x)
        x = _cast_input(x, model.dtype)
        y = _cast_labels(y, model.dtype)
        fm = jnp.asarray(fm, model.dtype) if fm is not None else None
        lm = jnp.asarray(lm, model.dtype) if lm is not None else None
        ew = jnp.asarray(ew, model.dtype) if ew is not None else None
        with obs.span("dp.step"):
            (model.params, (self._opt_flat, self._residual), model.state,
             _, loss) = self._step.dispatch(
                model.params, (self._opt_flat, self._residual), model.state,
                jnp.asarray(model.iteration, jnp.int32), model._next_rng(),
                x, y, fm, lm, (), ew)
        model.iteration += 1
        return loss

    def fit_batch_graph(self, batch, ew=None):
        """ComputationGraph step (mirrors ``model.fit_batch`` on an
        already-normalized ``(f, l, fm, lm)`` tuple batch)."""
        from deeplearning4j_tpu.train import resilience

        if not self._active:
            self.begin()
        model = self.model
        f, l, fm, lm = batch
        chaos = resilience.active_chaos()
        if chaos is not None:
            chaos.maybe_preempt(model.iteration)
            chaos.maybe_slow(model.iteration)
            f = chaos.maybe_nan_batch(model.iteration, f)
        ew = jnp.asarray(ew, model.dtype) if ew is not None else None
        with obs.span("dp.step"):
            (model.params, (self._opt_flat, self._residual), model.state,
             _, loss) = self._step.dispatch(
                model.params, (self._opt_flat, self._residual), model.state,
                jnp.asarray(model.iteration, jnp.int32), model._next_rng(),
                model._input_dict(f), l, model._mask_dict(fm), lm, {}, ew)
        model.iteration += 1
        return loss
