"""Device-mesh construction.

The mesh is the framework's one abstraction for every parallelism flavor:
- ``data``: data parallelism (replaces ParallelWrapper + both Spark masters)
- ``model``: tensor parallelism (sharded weight matrices; new capability —
  the reference has none, SURVEY.md §2.5)
- ``seq``: sequence/context parallelism for long sequences (ring attention
  lives on this axis)

Single-host multi-chip uses all local devices; multi-host uses
``jax.distributed.initialize`` + the same code (SPMD: every host runs the
same program over its address-local shard of the global batch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 on one axis means 'all remaining devices'.

    Axes: ``data`` (dp), ``model`` (tp — and ep: expert weights shard their
    expert axis here), ``seq`` (sp — ring attention), ``pipe`` (pp).
    """

    data: int = -1
    model: int = 1
    seq: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int, int, int]:
        d, m, s, p = self.data, self.model, self.seq, self.pipe
        fixed = max(m, 1) * max(s, 1) * max(p, 1)
        if d == -1:
            d = n_devices // fixed
        if d * m * s * p != n_devices:
            raise ValueError(
                f"MeshSpec {d}x{m}x{s}x{p} does not cover {n_devices} devices"
            )
        return d, m, s, p


def make_mesh(spec: Optional[MeshSpec] = None, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    spec = spec or MeshSpec()
    d, m, s, p = spec.resolve(len(devices))
    arr = np.array(devices).reshape(d, m, s, p)
    return Mesh(arr, ("data", "model", "seq", "pipe"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard axis 0 (batch) over the data axis, replicate the rest."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def data_axis_size(mesh: Mesh) -> int:
    """Replica count of the data-parallel exchange (the ``data`` axis)."""
    return mesh.shape["data"]


def data_sharded(mesh: Mesh) -> NamedSharding:
    """Shard axis 0 over ``data`` with no constraint on trailing axes — the
    layout of the explicit-exchange opt state (``[R, m]`` flat-shard stacks)
    and compression residuals (``[R, n_pad]``) in ``parallel/grads.py``."""
    return NamedSharding(mesh, P("data"))
