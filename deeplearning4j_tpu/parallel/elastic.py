"""Elastic membership runtime for multi-host data-parallel training.

The reference stack ran its cross-host regime over an Aeron parameter-server
layer (PAPER.md layer 5); this module is that layer's membership half for the
jax_graft port: a **lease-based rendezvous** on a shared coordination store,
on top of which ``train/elastic.py`` runs the compressed gradient exchange
(PR 3 ternary payloads over DCN) and the cross-replica sharded optimizer
update (arXiv 2004.13336) at whatever world size is currently alive.

Why not ``jax.distributed``: its world is fixed at init — a lost process
wedges every collective and the runtime cannot re-form at a reduced size.
Elasticity therefore lives ABOVE the XLA collectives: each worker is its own
single-process JAX instance (dense/ICI collectives stay inside the process,
where XLA is already optimal), and the cross-host exchange moves explicit
payloads through a :class:`FileStore` — a CRC-framed, atomically-renamed
key/value directory that stands in for the DCN fabric (etcd/Aeron in a real
fleet; a shared filesystem on localhost and in CI).

The membership protocol:

- **Leases** (``lease/<wid>``): each worker heartbeats a wall-clock
  timestamped lease every ``ttl/4`` seconds from a daemon thread. A worker
  whose lease is older than its TTL is dead to the group. The heartbeat
  thread can be suspended (``Membership.suspend``) — that IS the
  ``net_partition`` chaos fault: the worker keeps computing but its lease
  goes stale, exactly like a worker on the wrong side of a switch failure.
- **Views** (``view/<gen>``): membership agreement is a monotonic sequence
  of generation-numbered views, each recording ``members``,
  ``prev_members``, and the **sync point** (epoch/step/iteration) where the
  new world takes over. A view is proposed by the *coordinator* — the
  lowest worker id among live holders of the current view — via an
  exclusive create, so concurrent proposals for the same generation resolve
  to exactly one winner. Joiners cannot coordinate: only a state-holding
  member may propose, because the proposer's sync point must come from live
  training state.
- **Changes** surface as :class:`MembershipChanged` carrying the new view;
  the trainer drains to its step boundary, re-forms (re-sharding optimizer
  segments, see ``train/elastic.py``), and continues. A worker that finds
  itself expelled (partition healed after the TTL) re-leases and waits for
  the survivors to grow the view back around it — the in-process rejoin.

Observability: ``dl4j_workers_active`` gauge, ``dl4j_elastic_shrink_total``
/ ``dl4j_elastic_rejoin_total`` counters, and ``membership_change`` JSONL
events with rank/lease/epoch fields (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import warnings
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu import obs

__all__ = [
    "ElasticRuntime",
    "FileStore",
    "Membership",
    "MembershipChanged",
    "View",
    "elastic_knobs",
]


def elastic_knobs() -> dict:
    """Env-tunable membership timing (documented in docs/ROBUSTNESS.md)."""
    return {
        "ttl_s": float(os.environ.get("DL4J_TPU_ELASTIC_TTL_S", "10.0")),
        "poll_s": float(os.environ.get("DL4J_TPU_ELASTIC_POLL_S", "0.05")),
        "boot_timeout_s": float(
            os.environ.get("DL4J_TPU_ELASTIC_BOOT_TIMEOUT_S", "120.0")),
        "wait_timeout_s": float(
            os.environ.get("DL4J_TPU_ELASTIC_WAIT_TIMEOUT_S", "600.0")),
    }


# ---------------------------------------------------------------------------
# FileStore: CRC-framed atomic KV on a shared directory
# ---------------------------------------------------------------------------


_MAGIC = b"DLES"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32(payload), payload length

_LINK_FALLBACK_WARNED = False


def _count_rpc(op: str, backend: str) -> None:
    obs.counter("dl4j_store_rpc_total",
                "Coordination-store operations by op and backend",
                ("op", "backend")).inc(op=op, backend=backend)


class FileStore:
    """Shared coordination/payload store.

    Every record is framed ``magic | crc32 | length | payload`` and lands via
    write-to-tempfile + ``os.replace`` (or ``os.link`` for exclusive
    creates), so a reader sees either nothing or a whole, checksummed record
    — never a torn write. Keys are slash-separated paths under ``root``.

    The same interface (plus :meth:`watch`) is implemented over TCP by
    ``parallel/netstore.NetStore``; pick a backend with
    ``parallel.netstore.open_store`` / ``DL4J_TPU_STORE``.
    """

    backend = "file"

    def __init__(self, root):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)

    # -- paths --------------------------------------------------------------
    def _path(self, key: str) -> str:
        p = os.path.join(self.root, key)
        d = os.path.dirname(p)
        if d:
            os.makedirs(d, exist_ok=True)
        return p

    def _frame(self, data: bytes) -> bytes:
        return _HEADER.pack(_MAGIC, zlib.crc32(data) & 0xFFFFFFFF,
                            len(data)) + data

    def _tmp(self, path: str) -> str:
        return f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"

    # -- writes -------------------------------------------------------------
    def set(self, key: str, data: bytes) -> None:
        """Last-writer-wins atomic put (leases, payloads, manifests)."""
        _count_rpc("set", self.backend)
        path = self._path(key)
        tmp = self._tmp(path)
        with open(tmp, "wb") as f:
            f.write(self._frame(data))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def set_exclusive(self, key: str, data: bytes) -> bool:
        """First-writer-wins atomic put (view proposals). Returns True when
        THIS call created the record — the link is atomic, so exactly one of
        any number of concurrent proposers wins. Filesystems without
        hardlinks (FAT, some NFS exports) fall back to an ``O_EXCL``
        create: exclusivity holds, but the record is written in place, so a
        concurrent reader can catch it half-written — the CRC frame makes
        that read as missing, and the reader retries."""
        _count_rpc("setx", self.backend)
        path = self._path(key)
        tmp = self._tmp(path)
        framed = self._frame(data)
        with open(tmp, "wb") as f:
            f.write(framed)
            f.flush()
            os.fsync(f.fileno())
        try:
            try:
                os.link(tmp, path)
                return True
            except FileExistsError:
                return False
            except OSError:
                return self._set_exclusive_o_excl(path, framed)
        finally:
            os.unlink(tmp)

    def _set_exclusive_o_excl(self, path: str, framed: bytes) -> bool:
        global _LINK_FALLBACK_WARNED
        if not _LINK_FALLBACK_WARNED:
            _LINK_FALLBACK_WARNED = True
            warnings.warn(
                f"FileStore at {self.root!r}: os.link unsupported; exclusive "
                f"creates fall back to O_EXCL (exclusivity preserved, "
                f"in-place write guarded by CRC framing)",
                RuntimeWarning, stacklevel=3)
            obs.event("elastic_store_link_fallback", root=self.root)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "wb") as f:
            f.write(framed)
            f.flush()
            os.fsync(f.fileno())
        return True

    # -- reads --------------------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        """The record's payload, or None when missing. A record failing its
        CRC (torn external copy, disk fault) counts + reads as missing
        rather than poisoning the consumer."""
        _count_rpc("get", self.backend)
        path = os.path.join(self.root, key)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except (FileNotFoundError, NotADirectoryError):
            return None
        if len(raw) < _HEADER.size:
            return self._corrupt(key, "short_header")
        magic, crc, length = _HEADER.unpack_from(raw)
        payload = raw[_HEADER.size:]
        if magic != _MAGIC or len(payload) != length:
            return self._corrupt(key, "frame_mismatch")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return self._corrupt(key, "crc_mismatch")
        return payload

    def _corrupt(self, key: str, why: str) -> None:
        obs.counter("dl4j_elastic_store_corrupt_total",
                    "FileStore records failing frame/CRC validation").inc()
        obs.event("elastic_store_corrupt", key=key, reason=why,
                  backend=self.backend)
        return None

    def exists(self, key: str) -> bool:
        _count_rpc("exists", self.backend)
        return os.path.isfile(os.path.join(self.root, key))

    def delete(self, key: str) -> None:
        _count_rpc("delete", self.backend)
        try:
            os.unlink(os.path.join(self.root, key))
        except FileNotFoundError:
            pass

    def prune(self, prefix: str) -> None:
        """Best-effort recursive delete of a key subtree (step-payload GC).
        Concurrent readers are safe: records land by rename, so a reader
        either already opened the file (unlink doesn't revoke it) or sees a
        miss and falls into its normal wait path."""
        import shutil

        _count_rpc("prune", self.backend)
        shutil.rmtree(os.path.join(self.root, prefix), ignore_errors=True)

    def list(self, prefix: str) -> List[str]:
        """Sorted record names directly under the ``prefix`` directory."""
        _count_rpc("list", self.backend)
        d = os.path.join(self.root, prefix)
        try:
            names = os.listdir(d)
        except (FileNotFoundError, NotADirectoryError):
            return []
        return sorted(n for n in names if not n.endswith(".tmp")
                      and ".tmp." not in n)

    # -- watch ---------------------------------------------------------------
    def _fingerprint(self, prefix: str) -> Tuple:
        """State token for :meth:`watch`: (name, mtime_ns, size) of the
        entries directly under ``prefix``. Renaming a record into a
        subdirectory bumps that subdirectory's mtime, so watching ``""``
        observes changes anywhere in the tree one level down."""
        d = os.path.join(self.root, prefix) if prefix else self.root
        entries = []
        try:
            with os.scandir(d) as it:
                for e in it:
                    if e.name.endswith(".tmp") or ".tmp." in e.name:
                        continue
                    try:
                        st = e.stat()
                    except OSError:
                        continue
                    entries.append((e.name, st.st_mtime_ns, st.st_size))
        except (FileNotFoundError, NotADirectoryError):
            pass
        return tuple(sorted(entries))

    def watch(self, prefix: str, token=None, timeout: float = 1.0):
        """Block until something under ``prefix`` changes relative to
        ``token`` (or ``timeout`` elapses); returns the new opaque token.
        ``token=None`` returns the current token without waiting. The
        file backend polls directory fingerprints; the TCP backend long-
        polls a server revision — same contract, so membership waits are
        backend-agnostic."""
        _count_rpc("watch", self.backend)
        t0 = time.monotonic()
        cur = self._fingerprint(prefix)
        if token is None:
            return cur
        deadline = t0 + max(0.0, float(timeout))
        step = min(max(float(timeout) / 10.0, 0.005), 0.05)
        while cur == token:
            now = time.monotonic()
            if now >= deadline:
                break
            time.sleep(min(step, deadline - now))
            cur = self._fingerprint(prefix)
        obs.histogram("dl4j_store_watch_wait_seconds",
                      "Time spent blocked in store watch calls").observe(
                          time.monotonic() - t0)
        return cur

    # -- JSON convenience ---------------------------------------------------
    def set_json(self, key: str, value: dict) -> None:
        self.set(key, json.dumps(value, sort_keys=True).encode("utf-8"))

    def set_json_exclusive(self, key: str, value: dict) -> bool:
        return self.set_exclusive(
            key, json.dumps(value, sort_keys=True).encode("utf-8"))

    def get_json(self, key: str) -> Optional[dict]:
        raw = self.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return self._corrupt(key, "json_decode")


# ---------------------------------------------------------------------------
# Leases + heartbeat
# ---------------------------------------------------------------------------


class Membership:
    """One worker's lease on the group, renewed from a daemon thread.

    Lease timestamps are WALL clock by necessity — they are compared across
    processes, where no shared monotonic clock exists. All cross-process
    staleness math therefore lives in :meth:`_fresh`; purely local waits use
    ``time.monotonic()``.
    """

    def __init__(self, store: FileStore, wid: str, *, ttl: float,
                 poll: float, rack: str = ""):
        self.store = store
        self.wid = wid
        self.ttl = float(ttl)
        self.poll = float(poll)
        self.rack = str(rack)
        self.incarnation = f"{os.getpid()}.{int(time.time() * 1e6)}"  # graftlint: disable=monotonic-clock
        self._stop = threading.Event()
        self._suspend_until = 0.0       # monotonic deadline; 0 = not suspended
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- lease record -------------------------------------------------------
    def _write_lease(self) -> None:
        self.store.set_json(f"lease/{self.wid}", {
            "wid": self.wid,
            "ts": time.time(),  # graftlint: disable=monotonic-clock
            "ttl": self.ttl,
            "inc": self.incarnation,
            "rack": self.rack,
        })

    def _fresh(self, lease: Optional[dict]) -> bool:
        if not lease:
            return False
        age = time.time() - float(lease.get("ts", 0.0))  # graftlint: disable=monotonic-clock
        return age <= float(lease.get("ttl", self.ttl))

    # -- lifecycle ----------------------------------------------------------
    def join(self) -> None:
        """Write the first lease and start heartbeating. Re-entrant: a
        rejoining worker gets a fresh incarnation token."""
        self.incarnation = f"{os.getpid()}.{int(time.time() * 1e6)}"  # graftlint: disable=monotonic-clock
        self._write_lease()
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._heartbeat_loop, name=f"elastic-hb-{self.wid}",
                daemon=True)
            self._thread.start()

    def leave(self, timeout: Optional[float] = None) -> None:
        """Stop heartbeating, join the thread with a deadline (a heartbeat
        mid-RPC against an unreachable store can take up to its retry
        budget), then drop the lease best-effort."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=(2 * self.poll + 1.0)
                              if timeout is None else float(timeout))
            if self._thread.is_alive():
                obs.event("elastic_heartbeat_leak", wid=self.wid)
            self._thread = None
        try:
            self.store.delete(f"lease/{self.wid}")
        except OSError:
            pass  # store already gone; the lease will expire on its own

    def _heartbeat_loop(self) -> None:
        interval = max(self.ttl / 4.0, self.poll)
        while not self._stop.wait(interval):
            # check-and-renew under the lock: a suspend() landing between an
            # unlocked check and the write would be overridden by a renewal,
            # un-partitioning the worker mid-fault
            with self._lock:
                if time.monotonic() < self._suspend_until:
                    continue
                try:
                    self._write_lease()
                except OSError:
                    # store briefly unwritable: skip this beat; the TTL gives
                    # us ttl/interval more chances before anyone expels us
                    pass

    def suspend(self, seconds: float) -> None:
        """Stop renewing the lease for ``seconds`` (the net_partition /
        rack_partition chaos faults). The worker process keeps running; to
        the rest of the group it looks exactly like a network partition."""
        with self._lock:
            self._suspend_until = time.monotonic() + float(seconds)

    def heartbeat_now(self) -> None:
        """Synchronous renewal (called after a partition heals so rejoin
        does not wait for the next thread tick)."""
        with self._lock:
            self._suspend_until = 0.0
            self._write_lease()

    # -- group queries -------------------------------------------------------
    def lease(self, wid: str) -> Optional[dict]:
        return self.store.get_json(f"lease/{wid}")

    def live(self) -> List[str]:
        """Sorted worker ids whose lease is fresh right now."""
        out = []
        for name in self.store.list("lease"):
            if self._fresh(self.store.get_json(f"lease/{name}")):
                out.append(name)
        return sorted(out)

    def expired(self, wid: str) -> bool:
        return not self._fresh(self.lease(wid))


# ---------------------------------------------------------------------------
# Views
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class View:
    """One agreed membership generation and the sync point it starts at.

    ``incs`` records each member's lease *incarnation* (a per-process join
    token). A worker killed and relaunched under the same id re-leases with
    a fresh incarnation BEFORE the survivors notice the death; without the
    token they would keep waiting on a "live" member whose training state is
    gone. A member is therefore alive only while its lease is fresh AND its
    incarnation still matches the view's — a restarted process reads as
    dead-then-joiner, never as a state holder.
    """

    gen: int
    members: Tuple[str, ...]
    prev_members: Tuple[str, ...]
    epoch: int
    step: int
    iteration: int
    reason: str
    rejoined: Tuple[str, ...] = ()
    incs: Dict[str, str] = field(default_factory=dict)
    prev_incs: Dict[str, str] = field(default_factory=dict)
    # rack labels per member (DL4J_TPU_RACK), recorded at proposal time so
    # every member derives the SAME rack-aware mirror placement; prev_racks
    # keeps the outgoing geometry for handoff (mirrors of the old view)
    racks: Dict[str, str] = field(default_factory=dict)
    prev_racks: Dict[str, str] = field(default_factory=dict)

    @property
    def world(self) -> int:
        return len(self.members)

    def rank_of(self, wid: str) -> Optional[int]:
        try:
            return self.members.index(wid)
        except ValueError:
            return None

    def holders(self) -> Tuple[str, ...]:
        """Members carrying live training state across this view change:
        survivors of the previous view whose process never restarted. A
        relaunched same-id worker is in ``members`` (and maybe in
        ``prev_members``) but its incarnation changed — it takes the
        handoff, it does not serve it."""
        return tuple(m for m in self.members
                     if m in self.prev_members
                     and self.incs.get(m) == self.prev_incs.get(m))

    def to_json(self) -> dict:
        return {
            "gen": self.gen, "members": list(self.members),
            "prev_members": list(self.prev_members), "epoch": self.epoch,
            "step": self.step, "iteration": self.iteration,
            "reason": self.reason, "rejoined": list(self.rejoined),
            "incs": dict(self.incs), "prev_incs": dict(self.prev_incs),
            "racks": dict(self.racks), "prev_racks": dict(self.prev_racks),
        }

    @staticmethod
    def from_json(d: dict) -> "View":
        return View(
            gen=int(d["gen"]), members=tuple(d["members"]),
            prev_members=tuple(d.get("prev_members", ())),
            epoch=int(d.get("epoch", 0)), step=int(d.get("step", 0)),
            iteration=int(d.get("iteration", 0)),
            reason=str(d.get("reason", "")),
            rejoined=tuple(d.get("rejoined", ())),
            incs=dict(d.get("incs", {})),
            prev_incs=dict(d.get("prev_incs", {})),
            racks=dict(d.get("racks", {})),
            prev_racks=dict(d.get("prev_racks", {})))


class MembershipChanged(Exception):
    """Control-flow signal: a newer view exists (shrink, grow, or this
    worker's own expulsion). The trainer catches it at/above the step
    boundary and re-forms at ``self.view``."""

    def __init__(self, view: View):
        super().__init__(f"membership changed: gen {view.gen} "
                         f"({view.reason}; world {view.world})")
        self.view = view


def _view_key(gen: int) -> str:
    return f"view/{gen:08d}"


class ElasticRuntime:
    """Membership + view agreement for one worker of an elastic group."""

    def __init__(self, store: FileStore, wid: str, *,
                 ttl: Optional[float] = None, poll: Optional[float] = None,
                 rack: Optional[str] = None):
        knobs = elastic_knobs()
        self.store = store
        self.wid = wid
        self.ttl = float(knobs["ttl_s"] if ttl is None else ttl)
        self.poll = float(knobs["poll_s"] if poll is None else poll)
        self.wait_timeout = float(knobs["wait_timeout_s"])
        self.rack = str(os.environ.get("DL4J_TPU_RACK", "")
                        if rack is None else rack)
        self.membership = Membership(store, wid, ttl=self.ttl,
                                     poll=self.poll, rack=self.rack)
        self.view: Optional[View] = None

    # -- store-side view helpers -------------------------------------------
    def latest_view(self) -> Optional[View]:
        names = self.store.list("view")
        for name in reversed(names):
            d = self.store.get_json(f"view/{name}")
            if d is not None:
                return View.from_json(d)
        return None

    def _seen_key(self, wid: str) -> str:
        return f"seen/{wid}"

    def _lease_inc(self, wid: str) -> Optional[str]:
        lease = self.membership.lease(wid)
        return None if lease is None else str(lease.get("inc", ""))

    def _lease_rack(self, wid: str) -> str:
        lease = self.membership.lease(wid)
        return "" if lease is None else str(lease.get("rack", ""))

    def member_alive(self, wid: str) -> bool:
        """Alive AS THE MEMBER the adopted view admitted: fresh lease AND
        unchanged incarnation. A relaunched process under the same id has a
        fresh lease but a new incarnation — its training state is gone, so
        for membership purposes the member is dead (and the fresh lease is
        a joiner)."""
        lease = self.membership.lease(wid)
        if not self.membership._fresh(lease):
            return False
        want = (self.view.incs.get(wid)
                if self.view is not None else None)
        return want is None or str(lease.get("inc", "")) == want

    def _propose(self, members: Sequence[str], prev: Sequence[str],
                 sync: Tuple[int, int, int], reason: str) -> View:
        """Propose the next generation; return whatever view actually wins
        that generation (ours or a concurrent coordinator's)."""
        base = self.view.gen if self.view is not None else -1
        latest = self.latest_view()
        if latest is not None:
            base = max(base, latest.gen)
        gen = base + 1
        added = [m for m in members if m not in prev]
        rejoined = tuple(m for m in added
                         if self.store.exists(self._seen_key(m)))
        incs = {m: (self._lease_inc(m) or "") for m in members}
        racks = {m: self._lease_rack(m) for m in members}
        carry = (self.view is not None
                 and tuple(sorted(prev)) == self.view.members)
        prev_incs = dict(self.view.incs) if carry else {}
        prev_racks = dict(self.view.racks) if carry else {}
        cand = View(gen=gen, members=tuple(sorted(members)),
                    prev_members=tuple(sorted(prev)), epoch=sync[0],
                    step=sync[1], iteration=sync[2], reason=reason,
                    rejoined=rejoined, incs=incs, prev_incs=prev_incs,
                    racks=racks, prev_racks=prev_racks)
        if self.store.set_json_exclusive(_view_key(gen), cand.to_json()):
            return cand
        d = self.store.get_json(_view_key(gen))
        return View.from_json(d) if d else cand

    # -- adoption (metrics + events live here) ------------------------------
    def adopt(self, view: View) -> View:
        removed = sorted(set(view.prev_members) - set(view.members))
        added = sorted(set(view.members) - set(view.prev_members))
        rank = view.rank_of(self.wid)
        obs.gauge("dl4j_workers_active",
                  "Live workers in the adopted membership view").set(
                      view.world)
        if removed:
            obs.counter("dl4j_elastic_shrink_total",
                        "Workers expelled across adopted views").inc(
                            len(removed))
        if view.rejoined:
            obs.counter("dl4j_elastic_rejoin_total",
                        "Previously-seen workers re-admitted across adopted "
                        "views").inc(len(view.rejoined))
        obs.event("membership_change", gen=view.gen,
                  members=list(view.members), removed=removed, added=added,
                  rejoined=list(view.rejoined), reason=view.reason,
                  epoch=view.epoch, step=view.step,
                  iteration=view.iteration, rank=rank, wid=self.wid,
                  lease_ttl_s=self.ttl)
        if rank is not None:
            # membership history marker: a future re-admission of this wid
            # is a REJOIN, not a first join (counted separately above)
            self.store.set(self._seen_key(self.wid), b"1")
            # fleet identity: every span/event this process records from
            # here on carries its rank/incarnation, so the collector and
            # the merged trace can tell the workers apart
            obs.set_process_context(rank=rank, wid=self.wid,
                                    incarnation=self.membership.incarnation)
        self.view = view
        return view

    # -- bootstrap ----------------------------------------------------------
    def bootstrap(self, world: int,
                  timeout: Optional[float] = None) -> View:
        """Join and agree on an initial view.

        Three ways in: (a) fresh group — wait for ``world`` live leases, the
        lowest wid proposes generation 0; (b) rejoin — a run is in progress
        (live holders of the latest view exist), wait for them to grow the
        view around us; (c) restart — views exist but no holder is alive
        (full-group preemption), the lowest live wid proposes a
        ``restart`` view with no state holders, and every worker restores
        from the distributed checkpoint.
        """
        knobs = elastic_knobs()
        timeout = knobs["boot_timeout_s"] if timeout is None else timeout
        self.membership.join()
        deadline = time.monotonic() + timeout
        token = self.store.watch("", None)
        while True:
            latest = self.latest_view()
            if (latest is not None and self.wid in latest.members
                    and latest.incs.get(self.wid)
                    == self.membership.incarnation):
                return self.adopt(latest)
            live = self.membership.live()
            if latest is None:
                if len(live) >= world and live and live[0] == self.wid:
                    view = self._propose(live, (), (0, 0, 0), "bootstrap")
                    return self.adopt(view)
            else:
                holders = [m for m in latest.members
                           if m != self.wid and m in live
                           and latest.incs.get(m) == self._lease_inc(m)]
                if not holders:
                    # no live state holder: full-group restart from durable
                    # checkpoints (the proposer carries no training state,
                    # which is fine — nobody's is live)
                    if live and live[0] == self.wid:
                        view = self._propose(
                            live, (), (latest.epoch, latest.step,
                                       latest.iteration), "restart")
                        return self.adopt(view)
                # else: run in progress — the survivors' coordinator grows
                # the view around our fresh lease at their next boundary
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic bootstrap: worker {self.wid!r} saw "
                    f"{len(live)}/{world} live workers and no adoptable "
                    f"view within {timeout:.0f}s")
            # wake on any store change (lease writes, view creates) or after
            # one poll interval — lease EXPIRY makes no store event, so the
            # timeout bound is what notices silent deaths
            token = self.store.watch("", token, timeout=self.poll)

    # -- steady-state polling -----------------------------------------------
    def newer_view(self) -> Optional[View]:
        latest = self.latest_view()
        if latest is not None and (self.view is None
                                   or latest.gen > self.view.gen):
            return latest
        return None

    def check_for_change(self) -> None:
        """Raise :class:`MembershipChanged` when the store has moved past
        our adopted view (cheap; called inside payload waits)."""
        nv = self.newer_view()
        if nv is not None:
            raise MembershipChanged(nv)

    def poll_boundary(self, sync: Tuple[int, int, int]) -> None:
        """Step-boundary membership poll — the ONLY place grows happen, so a
        mid-step join never tears a step in half. Raises
        :class:`MembershipChanged` when a newer view exists or this call
        proposes one (lease lost → shrink, fresh lease → grow/rejoin)."""
        self.check_for_change()
        view = self.view
        live = self.membership.live()
        dead = [m for m in view.members if not self.member_alive(m)]
        joiners = [w for w in live if w not in view.members or w in dead]
        if not dead and not joiners:
            return
        holders = [m for m in view.members if m not in dead]
        if not holders:
            return  # we lost our own lease too; expulsion surfaces elsewhere
        if holders[0] != self.wid:
            # not the coordinator: the change is real, but only the
            # coordinator proposes; we either see its view next poll or
            # propose ourselves once its lease expires
            return
        members = holders + joiners
        reason = ("reform" if (dead and joiners)
                  else "shrink" if dead else "grow")
        nv = self._propose(members, view.members, sync, reason)
        raise MembershipChanged(nv)

    def report_dead(self, wids: Sequence[str],
                    sync: Tuple[int, int, int]) -> None:
        """A payload wait proved ``wids`` unrecoverable mid-step (lease
        expired AND no mirror can serve). Drive a shrink: coordinator
        proposes, everyone else waits for the winning view. Always raises
        :class:`MembershipChanged` (or times out)."""
        view = self.view
        deadline = time.monotonic() + self.wait_timeout
        token = self.store.watch("", None)
        while True:
            self.check_for_change()
            live = self.membership.live()
            holders = [m for m in view.members
                       if m not in wids and self.member_alive(m)]
            if holders and holders[0] == self.wid:
                joiners = [w for w in live
                           if w not in holders and w not in wids]
                nv = self._propose(holders + joiners, view.members, sync,
                                   "shrink")
                raise MembershipChanged(nv)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic shrink: no coordinator produced a view "
                    f"excluding {list(wids)} within "
                    f"{self.wait_timeout:.0f}s")
            token = self.store.watch("view", token, timeout=self.poll)

    def await_readmission(self, should_stop=None) -> Optional[View]:
        """Expelled-worker path (partition healed past the TTL): renew the
        lease and wait for the survivors to grow a view that includes us.
        ``should_stop`` (optional callable) lets the caller abort the wait —
        e.g. when the job finished while we were on the wrong side of the
        partition and nobody is left to re-admit us; returns None then."""
        self.membership.heartbeat_now()
        obs.event("elastic_rejoin_wait", wid=self.wid,
                  gen=self.view.gen if self.view else -1)
        deadline = time.monotonic() + self.wait_timeout
        token = self.store.watch("view", None)
        while True:
            latest = self.latest_view()
            if (latest is not None and self.wid in latest.members
                    and latest.incs.get(self.wid)
                    == self.membership.incarnation
                    and (self.view is None or latest.gen > self.view.gen)):
                return latest
            if should_stop is not None and should_stop():
                obs.event("elastic_rejoin_abandoned", wid=self.wid)
                return None
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic rejoin: worker {self.wid!r} was not "
                    f"re-admitted within {self.wait_timeout:.0f}s")
            # new views are store writes, so the watch wakes promptly; the
            # timeout keeps should_stop responsive
            token = self.store.watch("view", token,
                                     timeout=max(self.poll, 0.05))

    # -- teardown -----------------------------------------------------------
    def leave(self) -> None:
        self.membership.leave()
