"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability (first-class in this framework; the reference tops
out at truncated BPTT — SURVEY.md §5.7). Each device holds a block of the
sequence; K/V blocks rotate around the ring via ``ppermute`` over ICI while
every device accumulates its queries' attention online (numerically-stable
streaming softmax, the FlashAttention/RingAttention recurrence). Peak memory
per chip is O(T/seq · T/seq) instead of O(T²), and the K/V transfer for step
i+1 overlaps with the compute of step i (XLA schedules the ppermute DMA
concurrently with the einsums).

Composition: the per-shard kernel `_ring_attention_shard` runs inside
``shard_map``; `ring_self_attention` wraps it for direct use under a mesh
with dp on "data" and sp on "seq".
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax>=0.6 top-level, older: experimental
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

_NEG_BIG = -1e30


def _block_attend(q, k, v, scale, q_off, k_off, causal, m, l, acc, kmask=None):
    """One block of the streaming-softmax recurrence.

    q: [B,Tq,H,D] local queries; k/v: [B,Tk,H,D] current ring block.
    m/l/acc: running max [B,H,Tq], normalizer [B,H,Tq], output [B,Tq,H,D].
    kmask: [B,Tk] key validity (1=real, 0=padding) for this block.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        s = jnp.where(kpos[None, None, None, :] > qpos[None, None, :, None], -jnp.inf, s)
    if kmask is not None:
        s = jnp.where(kmask[:, None, None, :] > 0, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    m_new = jnp.maximum(m_new, _NEG_BIG)  # keep finite when a block is fully masked
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    acc_new = acc * jnp.transpose(corr, (0, 2, 1))[..., None] + pv
    return m_new, l_new, acc_new


def _ring_attention_shard(q, k, v, kmask, *, axis_name: str, causal: bool):
    """Ring attention on per-device shards [B, T_local, H, D] (call inside
    shard_map with the sequence sharded over ``axis_name``). ``kmask`` is the
    per-shard key-validity mask [B, T_local] (or None)."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    scale = 1.0 / (D**0.5)
    # Accumulate in f32 even for bf16 activations: l sums thousands of exp
    # terms and acc is rescaled every ring step — bf16 compounds ~1e-2 error.
    out_dtype = q.dtype
    acc_dtype = jnp.float32 if q.dtype == jnp.bfloat16 else q.dtype
    m = jnp.full((B, H, Tq), _NEG_BIG, acc_dtype)
    l = jnp.zeros((B, H, Tq), acc_dtype)
    acc = jnp.zeros(q.shape, acc_dtype)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    q_off = my_idx * Tq

    def step(i, carry):
        k_cur, v_cur, km_cur, m, l, acc = carry
        src = (my_idx - i) % axis_size  # which rank's block we now hold
        m, l, acc = _block_attend(
            q, k_cur, v_cur, scale, q_off, src * Tq, causal, m, l, acc, km_cur
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        km_nxt = lax.ppermute(km_cur, axis_name, perm) if km_cur is not None else None
        return k_nxt, v_nxt, km_nxt, m, l, acc

    # Static Python loop: axis_size is known at trace time, blocks stay
    # unrolled so XLA overlaps each step's ppermute with the next einsum.
    carry = (k, v, kmask, m, l, acc)
    for i in range(axis_size):
        carry = step(i, carry)
    _, _, _, m, l, acc = carry
    l = jnp.maximum(l, 1e-20)
    return (acc / jnp.transpose(l, (0, 2, 1))[..., None]).astype(out_dtype)


def _ring_flash_shard(q, k, v, kmask=None, *, axis_name: str, causal: bool,
                      interpret: bool):
    """Flash-backed ring attention shard (round 4): each arriving k/v block
    is attended with the Pallas chunked kernel and the partials merge by
    the streaming-softmax identity — fully differentiable (the blocks'
    custom VJP carries the lse cotangent), and no [Tq, Tk] score tensor
    ever exists.

    The causal structure needs NO absolute positions: the diagonal block is
    always ring step 0 (k is each shard's OWN block before any permute), so
    step 0 runs the local causal kernel; every later step is either fully
    allowed (source shard strictly before ours) or fully masked — a traced
    where() on the block's lse (weight -> 0) handles that, keeping block
    offsets static. ``kmask`` [B, T_local]: this shard's key validity; it
    rotates around the ring with its k/v block and feeds the chunk kernel's
    per-key-block mask (round 5)."""
    from deeplearning4j_tpu.ops.flash_attention import (
        flash_attention_block_grad, merge_attention_blocks)

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    parts = []
    kc, vc, kmc = k, v, kmask
    for i in range(axis_size):          # static unroll, like the XLA ring
        o_i, lse_i = flash_attention_block_grad(
            q, kc, vc, kmask=kmc, causal=(causal and i == 0),
            interpret=interpret)
        if causal and i > 0:
            src = (my_idx - i) % axis_size       # which shard's block this is
            allowed = src < my_idx               # strictly-past blocks only
            lse_i = jnp.where(allowed, lse_i, _NEG_BIG)
        parts.append((o_i, lse_i))
        if i + 1 < axis_size:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            if kmc is not None:
                kmc = lax.ppermute(kmc, axis_name, perm)
    return merge_attention_blocks(parts)


def local_attention(q, k, v, *, causal: bool = False, kmask=None):
    """Single-device reference attention, same layout [B,T,H,D].
    ``kmask`` [B,T]: 1=real key, 0=padding (excluded from attention)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        msk = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(msk[None, None], s, -jnp.inf)
    if kmask is not None:
        s = jnp.where(kmask[:, None, None, :] > 0, s, -jnp.inf)
    # guard fully-masked rows (all -inf) against NaN softmax
    s = jnp.maximum(s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_self_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    causal: bool = False,
    kmask=None,
    data_axis: Optional[str] = "data",
    seq_axis: str = "seq",
    head_axis: Optional[str] = None,
    use_flash: bool = False,
):
    """shard_map-wrapped ring attention: batch over ``data_axis``, sequence
    blocks over ``seq_axis``. Pass ``head_axis="model"`` when q/k/v are
    head-sharded by tensor parallelism (column-parallel Wqkv) so the kernel
    runs on local heads instead of forcing an all-gather over the model axis.
    ``use_flash=True`` runs each ring block through the Pallas chunked
    kernel with exact streaming-softmax merging — no per-block score
    tensor, fully differentiable; a kmask rides the ring alongside its
    k/v block. Inputs/outputs [B, T, H, D] global arrays; kmask [B, T]
    or None."""
    spec = P(data_axis, seq_axis, head_axis, None)
    mspec = P(data_axis, seq_axis)
    if use_flash:
        fn_flash = functools.partial(
            _ring_flash_shard, axis_name=seq_axis, causal=causal,
            interpret=jax.default_backend() != "tpu")
        in_specs = (spec, spec, spec) if kmask is None else (spec, spec, spec, mspec)
        args = (q, k, v) if kmask is None else (q, k, v, kmask)
        try:
            # pallas_call outputs carry no vma annotation; disable the
            # shard_map varying-axes check for this (correct) spec
            return shard_map(fn_flash, mesh=mesh, in_specs=in_specs,
                             out_specs=spec, check_vma=False)(*args)
        except TypeError:
            pass
        try:  # jax 0.4/0.5 spell the same knob check_rep
            return shard_map(fn_flash, mesh=mesh, in_specs=in_specs,
                             out_specs=spec, check_rep=False)(*args)
        except TypeError:  # neither parameter exists
            return shard_map(fn_flash, mesh=mesh, in_specs=in_specs,
                             out_specs=spec)(*args)
    fn = functools.partial(_ring_attention_shard, axis_name=seq_axis, causal=causal)
    if kmask is None:
        def fn_nomask(q, k, v):
            return fn(q, k, v, None)

        return shard_map(fn_nomask, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec)(
        q, k, v, kmask
    )
