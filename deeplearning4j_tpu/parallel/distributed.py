"""Multi-host (multi-process) training setup.

Capability parity with the reference's Spark scaleout value proposition —
multi-NODE training (dl4j-spark ParameterAveragingTrainingMaster.java:308,
SharedTrainingMaster.java:304) — re-designed TPU-first: instead of a Spark
driver shipping parameter/gradient messages, every host runs the SAME SPMD
program under ``jax.distributed``; the mesh spans all hosts' devices and XLA
lowers the gradient psum onto ICI/DCN. There is no separate "training
master": ``ParallelWrapper`` works unchanged, with each host feeding its
process-local shard of the global batch.

On real TPU pods, ``init_distributed()`` with no arguments picks up the TPU
runtime's cluster environment. For CPU testing (and CI), pass the
coordinator/process arguments explicitly and collectives run over gloo —
tests/test_multihost.py launches 2 processes x 4 virtual devices and asserts
loss parity with a single-process run.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_count: Optional[int] = None,
                     cpu_collectives: str = "gloo") -> None:
    """Join (or form) a multi-process JAX cluster.

    Must run before any JAX backend initialization. On TPU pods all arguments
    are optional (the plugin discovers the cluster); on CPU/GPU pass
    ``coordinator_address`` ("host:port"), ``num_processes``, ``process_id``.
    ``local_device_count``: virtual CPU devices for this process (testing).
    """
    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={local_device_count}"
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()

    import jax

    if cpu_collectives and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_cpu_collectives_implementation", cpu_collectives)
    # Forward each argument independently — jax.distributed.initialize
    # accepts any subset (the rest come from the environment / TPU runtime).
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def shutdown_distributed() -> None:
    import jax

    jax.distributed.shutdown()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_multihost() -> bool:
    return process_count() > 1


def global_array(mesh, local_data: np.ndarray, spec=None):
    """Assemble a jax.Array sharded over ``mesh`` from this process's local
    rows. ``spec`` defaults to batch-sharding over the ``data`` axis. In
    single-process mode this is a plain device_put (same semantics)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if spec is None:
        spec = P("data", *([None] * (np.ndim(local_data) - 1)))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local_data, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local_data))


def replicate_global(mesh, tree):
    """Replicate a pytree onto every device of a (possibly multi-host) mesh.
    Every process must hold the same values (guaranteed when params were
    initialized from the same seed). Leaves already carrying the target
    sharding pass through untouched (no D2H round-trip on repeated calls)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    multihost = jax.process_count() > 1

    def put(a):
        if isinstance(a, jax.Array) and a.sharding == repl:
            return a
        if multihost:
            return jax.make_array_from_process_local_data(repl, np.asarray(a))
        return jax.device_put(a, repl)

    return jax.tree_util.tree_map(put, tree)
