"""Data-parallel training over a device mesh.

Capability parity with ParallelWrapper
(/root/reference/deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/
src/main/java/org/deeplearning4j/parallelism/ParallelWrapper.java:58) and the
Spark TrainingMasters — re-designed TPU-first. Where the reference spawns one
replica thread per device and averages parameters every N iterations (or
threshold-encodes gradient updates into a shared ring buffer), here the SAME
jitted step the single-chip path uses is simply fed a globally-sharded batch:
params live replicated on every chip, the batch is split along the ``data``
mesh axis, and XLA inserts the gradient all-reduce (psum over ICI) during
compilation. Parameter averaging, gradient sharing, and the parameter server
are all THIS one mechanism — exact (no compression loss), synchronous, and
overlapped with backprop by the compiler.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn.model import _iter_batches
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.train.listeners import close_listeners
from deeplearning4j_tpu.utils import bucketing
from deeplearning4j_tpu.utils.bucketing import padded_label_mask, tile_pad


def _env_flag(name: str) -> Optional[bool]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    return raw != "0"

# DP sharding and shape bucketing share one padding mechanism (tiled rows +
# zero-weighted loss); the canonical implementation lives in utils.bucketing.
# Kept as a module name here for compatibility with existing callers.
_tile_pad = tile_pad


class ParallelWrapper:
    """Drop-in accelerator for a MultiLayerNetwork/ComputationGraph: same
    ``fit`` surface, batch sharded over the mesh's ``data`` axis.

    Usage::

        pw = ParallelWrapper(model)          # all local devices
        pw.fit((x, y), epochs=10, batch_size=512)

    The global batch must divide by the data-axis size (the reference
    round-robins whole DataSets to workers; here the sharding is exact).
    """

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 grad_compress: Optional[bool] = None,
                 sharded_update: Optional[bool] = None,
                 compress_threshold: Optional[float] = None):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh(MeshSpec())
        self.n_data = self.mesh.shape["data"]
        self._repl = NamedSharding(self.mesh, P())
        # Explicit-exchange switches (parallel/grads.py): kwargs win, then
        # env (DL4J_TPU_GRAD_COMPRESS / DL4J_TPU_SHARDED_UPDATE /
        # DL4J_TPU_COMPRESS_THRESHOLD), default OFF — on a single
        # ICI-connected slice the implicit dense psum is already optimal;
        # see docs/PERF.md "Compressed collectives & sharded weight updates".
        if grad_compress is None:
            grad_compress = _env_flag("DL4J_TPU_GRAD_COMPRESS")
        if sharded_update is None:
            sharded_update = _env_flag("DL4J_TPU_SHARDED_UPDATE")
        if compress_threshold is None:
            compress_threshold = float(
                os.environ.get("DL4J_TPU_COMPRESS_THRESHOLD", "1e-3"))
        self.grad_compress = bool(grad_compress)
        self.sharded_update = bool(sharded_update)
        self.compress_threshold = float(compress_threshold)
        self._runner = None
        # Multi-host (jax.distributed): every process runs this same fit()
        # on its process-LOCAL batch rows; global batch = concat over
        # processes in process order. Per-host batch sizes may be UNEVEN
        # (MLN path): hosts equalize padded sizes via process_allgather and
        # the loss rescale uses the GLOBAL real-row count, so the result
        # equals a single-process run on the concatenated batch exactly
        # (tests/test_multihost.py). Padding granularity is the per-process
        # shard count.
        self._nproc = jax.process_count()
        self._pad_quantum = max(self.n_data // self._nproc, 1)

    def _shard(self, arr):
        if arr is None:
            return None
        from deeplearning4j_tpu.parallel.distributed import global_array

        arr = np.asarray(arr)  # before .ndim: lists welcome
        if arr.dtype.kind not in "iub":
            # preserve integer/bool arrays: token-id features and sparse
            # class labels must not round-trip through the float model dtype
            arr = arr.astype(self.model.dtype)
        spec = P("data", *([None] * (arr.ndim - 1)))
        return global_array(self.mesh, arr, spec)

    def _replicate_model(self):
        from deeplearning4j_tpu.parallel.distributed import replicate_global

        self.model.params = replicate_global(self.mesh, self.model.params)
        self.model.state = replicate_global(self.mesh, self.model.state)
        if self.model.opt_state is not None:
            self.model.opt_state = replicate_global(self.mesh, self.model.opt_state)

    def _pad_to_shardable(self, arrs, record: bool = False):
        """Tile members of a batch so the leading axis divides n_data —
        rounded UP the shared bucketing ladder first (utils.bucketing), so DP
        fit with ragged batch sizes reuses a bounded set of compiled
        executables exactly like the single-chip path (every distinct padded
        size is a fresh XLA compile of the sharded step). Disable via
        DL4J_TPU_BUCKETING=0 to pad only to the shard count.

        Padded rows repeat real examples (benign numerics for batch-coupled
        ops) but MUST be zero-weighted in the loss by the caller — see
        ``_padded_lmask`` — or they would silently double-weight samples in
        the gradient."""
        n = next(len(a) for a in arrs if a is not None)
        q = self._pad_quantum
        target = bucketing.bucket_size(n) if (
            bucketing.bucketing_enabled() and n > 0) else n
        target = max(target, q if n == 0 else n)
        target = -(-target // q) * q            # round up to the shard quantum
        if record:
            bucketing.telemetry().record_hit("dp.fit", n, target)
        if target == n and n > 0:
            return arrs, n
        return tuple(_tile_pad(a, target - n) for a in arrs), n

    def _even_multihost(self, arrs, n):
        """Equalize each process's PADDED local row count to the global max
        (global_array needs equal per-process shards) and return the global
        real-row count + global padded batch size.

        The allgather runs EVERY batch on purpose: it is a collective, and
        skip-when-locally-unchanged caching would deadlock the moment one
        host's batch size changes while another's repeats (each host can
        only see its own key). It moves 16 bytes; the per-batch cost is a
        host-side round-trip, negligible next to the training step."""
        from jax.experimental import multihost_utils

        local = next(len(a) for a in arrs if a is not None)
        info = multihost_utils.process_allgather(
            np.asarray([n, local], np.int64))
        info = np.asarray(info).reshape(self._nproc, 2)
        n_tot = int(info[:, 0].sum())
        target = int(info[:, 1].max())
        if local < target:
            arrs = tuple(_tile_pad(a, target - local) for a in arrs)
        return arrs, n_tot, target * self._nproc

    def _padded_lmask(self, y, lm, n, scale=None):
        """Label mask zero-weighting padded rows [n:] so the jitted step's
        loss averages over the n REAL examples only (exact equivalence with
        the unpadded single-device fit). Canonical implementation — and the
        full derivation of the B_pad/n pre-scaling against average_score's
        branches — lives in utils.bucketing.padded_label_mask."""
        return padded_label_mask(y, lm, n, scale=scale)

    def _exchange_runner(self):
        """The explicit-exchange step runner (parallel/grads.py), or None
        when the implicit dense path applies (both switches off). Built once
        and kept — its compression residuals must persist across fit calls."""
        if not (self.grad_compress or self.sharded_update):
            return None
        if self._nproc > 1:
            warnings.warn(
                "DL4J_TPU_GRAD_COMPRESS/DL4J_TPU_SHARDED_UPDATE are "
                "single-process only for now; multi-host fit falls back to "
                "the implicit dense exchange", stacklevel=3)
            return None
        if self._runner is None:
            from deeplearning4j_tpu.parallel.grads import DataParallelStep

            self._runner = DataParallelStep(
                self.model, self.mesh, compress=self.grad_compress,
                sharded_update=self.sharded_update,
                threshold=self.compress_threshold)
        return self._runner

    def _restore_runner_residuals(self, runner) -> None:
        """Hand checkpointed compression residuals (stashed on the model by
        resume/restore) to the exchange runner — must happen after begin(),
        which otherwise seeds zeros."""
        pending = getattr(self.model, "_pending_residuals", None)
        if pending:
            runner.load_residuals(pending)
            self.model._pending_residuals = None

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            resume_from=None):
        """Data-parallel fit: identical semantics to ``model.fit`` on a batch
        ``batch_size`` large, executed across all chips.

        ``resume_from``: a CheckpointListener directory — restore the newest
        VALID checkpoint (including the flat-opt snapshot and compression
        residuals a DP checkpoint carries) and continue; ``epochs`` becomes
        the TOTAL budget and the interrupted epoch skips its consumed
        batches (same contract as model.fit; docs/ROBUSTNESS.md)."""
        if self.model.params is None:
            self.model.init()
        resume_skip = 0
        if resume_from is not None:
            from deeplearning4j_tpu.train import resilience

            if resilience.resume(self.model, resume_from) is not None:
                resume_skip = int(getattr(self.model, "batch_in_epoch", 0))
                epochs = max(epochs - self.model.epoch, 0)
                # rebuild the exchange plan around the restored state (the
                # restored LR scale may have produced new updater objects)
                self._runner = None
        self._replicate_model()
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            return self._fit_graph(data, epochs, batch_size, resume_skip)
        model = self.model
        guard = getattr(model, "divergence_guard", None)
        runner = self._exchange_runner()
        if runner is not None:
            runner.begin()
            self._restore_runner_residuals(runner)
        try:
            for _ in range(epochs):
                skip_n, resume_skip = resume_skip, 0
                model.batch_in_epoch = skip_n
                for l in model.listeners:
                    l.on_epoch_start(model, model.epoch)
                source = data() if callable(data) else data
                batch_iter = _iter_batches(source, batch_size)
                for _ in range(skip_n):
                    # resume: skip the interrupted epoch's consumed batches
                    # (the restored RNG key is already past them)
                    if next(batch_iter, None) is None:
                        break
                for batch in batch_iter:
                    # pad so the batch shards exactly (the reference
                    # round-robins whole DataSets to workers; here the split
                    # must be even), then zero-weight the padded rows in the
                    # loss; ew excludes them from batch-coupled statistics
                    # (BatchNorm)
                    (x, y, fm, lm), n = self._pad_to_shardable(
                        batch, record=True)
                    if self._nproc > 1:
                        (x, y, fm, lm), n_tot, gB = self._even_multihost(
                            (x, y, fm, lm), n)
                        # global rescale: every real row weighs gB/n_tot so
                        # the loss equals the single-process mean over n_tot
                        # rows even when hosts contribute different row counts
                        lm = (self._padded_lmask(y, lm, n, scale=gB / n_tot)
                              if n_tot != gB or lm is not None else lm)
                        padded = n_tot != gB
                    else:
                        lm = self._padded_lmask(y, lm, n)
                        padded = len(x) != n
                    ew = None
                    if padded:
                        ew = np.zeros(len(x), np.float32)
                        ew[:n] = 1.0
                    args = (self._shard(x), self._shard(y), self._shard(fm),
                            self._shard(lm))
                    with obs.span("dp.fit_batch"):
                        score = (runner.fit_batch(*args, ew=self._shard(ew))
                                 if runner is not None
                                 else model._fit_batch(*args, ew=self._shard(ew)))
                    model.batch_in_epoch += 1
                    if guard is not None:
                        guard.observe(model, score)
                        # rollback may swap the runner's carries under us —
                        # nothing to do here: runner.reload() re-entered the
                        # exchange layout before observe() returned
                    if model.listeners:
                        score = float(score)
                        from deeplearning4j_tpu.train import resilience

                        resilience.note_score(score)
                        for l in model.listeners:
                            l.iteration_done(model, model.iteration, score, n)
                if guard is not None:
                    guard.flush(model)
                for l in model.listeners:
                    l.on_epoch_end(model, model.epoch)
                model.epoch += 1
        finally:
            if runner is not None:
                runner.finish()
            # same teardown contract as model.fit: stop in-flight
            # ProfilerListener traces even when the loop exits early
            close_listeners(model.listeners)
        return model

    def _fit_graph(self, data, epochs: int, batch_size: Optional[int],
                   resume_skip: int = 0):
        """ComputationGraph variant: shard every member of the MultiDataSet
        (features/labels/masks tuples) along the data axis."""
        model = self.model
        shard_t = lambda t: tuple(self._shard(a) for a in t) if t is not None else None
        runner = self._exchange_runner()
        if runner is not None:
            runner.begin()
            self._restore_runner_residuals(runner)
        try:
            self._fit_graph_loop(data, epochs, batch_size, shard_t, runner,
                                 resume_skip)
        finally:
            if runner is not None:
                runner.finish()
            close_listeners(model.listeners)
        return model

    def _fit_graph_loop(self, data, epochs, batch_size, shard_t, runner,
                        resume_skip: int = 0):
        model = self.model
        guard = getattr(model, "divergence_guard", None)
        for _ in range(epochs):
            skip_n, resume_skip = resume_skip, 0
            model.batch_in_epoch = skip_n
            for l in model.listeners:
                l.on_epoch_start(model, model.epoch)
            source = data() if callable(data) else data
            batch_iter = model._iter_multi(source, batch_size)
            for _ in range(skip_n):
                # resume: skip the interrupted epoch's consumed batches
                if next(batch_iter, None) is None:
                    break
            for f, lbl, fm, lm in batch_iter:
                f, n = self._pad_to_shardable(f, record=True)
                if lbl is not None:
                    lbl, _ = self._pad_to_shardable(lbl)
                if fm is not None:
                    fm, _ = self._pad_to_shardable(fm)
                if lm is not None:
                    lm, _ = self._pad_to_shardable(lm)
                scale = None
                if self._nproc > 1:
                    # equalize padded sizes + global loss rescale, jointly
                    # over every MultiDataSet member (same mechanism as the
                    # MLN path — uneven per-host batches stay exact)
                    lens = [len(t) if t is not None else 0
                            for t in (f, lbl, fm, lm)]
                    flat = sum((list(t) for t in (f, lbl, fm, lm)
                                if t is not None), [])
                    flat, n_tot, gB = self._even_multihost(tuple(flat), n)
                    flat = list(flat)
                    parts = []
                    for ln, t in zip(lens, (f, lbl, fm, lm)):
                        parts.append(tuple(flat[:ln]) if t is not None else None)
                        flat = flat[ln:]
                    f, lbl, fm, lm = parts
                    if n_tot != gB:
                        scale = gB / n_tot
                    padded = n_tot != gB
                else:
                    padded = len(f[0]) != n
                if lbl is not None and (padded or lm is not None):
                    # zero-weight padded rows in every output's loss
                    lms = lm if lm is not None else (None,) * len(lbl)
                    lm = tuple(
                        self._padded_lmask(yi, lmi, n, scale=scale)
                        for yi, lmi in zip(lbl, lms)
                    )
                    if all(m is None for m in lm):
                        lm = None
                ew = None
                total = len(f[0])
                if padded:
                    # exclude padded rows from batch-coupled statistics
                    # (BatchNorm vertices) — same channel as the MLN path
                    ew = np.zeros(total, np.float32)
                    ew[:n] = 1.0
                sharded = (shard_t(f), shard_t(lbl), shard_t(fm), shard_t(lm))
                with obs.span("dp.fit_batch"):
                    score = (runner.fit_batch_graph(sharded, ew=self._shard(ew))
                             if runner is not None
                             else model.fit_batch(sharded, ew=self._shard(ew)))
                model.batch_in_epoch += 1
                if guard is not None:
                    guard.observe(model, score)
                if model.listeners:
                    score = float(score)
                    from deeplearning4j_tpu.train import resilience

                    resilience.note_score(score)
                    for l in model.listeners:
                        l.iteration_done(model, model.iteration, score, n)
            if guard is not None:
                guard.flush(model)
            for l in model.listeners:
                l.on_epoch_end(model, model.epoch)
            model.epoch += 1
        return model

    def output(self, x):
        """Sharded batched inference across the mesh (uneven batches are
        padded for the sharded call and trimmed from the result)."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(x, (tuple, list)):
            xs, n = self._pad_to_shardable(tuple(np.asarray(a) for a in x))
            if isinstance(self.model, ComputationGraph):
                out = self.model.output(*[self._shard(a) for a in xs])
            else:
                out = self.model.output(self._shard(xs[0]))
            trim = lambda o: o[:n]
            return jax.tree_util.tree_map(trim, out)
        (xp,), n = self._pad_to_shardable((np.asarray(x),))
        out = self.model.output(self._shard(xp))
        return jax.tree_util.tree_map(lambda o: o[:n], out)
