"""Data-parallel training over a device mesh.

Capability parity with ParallelWrapper
(/root/reference/deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/
src/main/java/org/deeplearning4j/parallelism/ParallelWrapper.java:58) and the
Spark TrainingMasters — re-designed TPU-first. Where the reference spawns one
replica thread per device and averages parameters every N iterations (or
threshold-encodes gradient updates into a shared ring buffer), here the SAME
jitted step the single-chip path uses is simply fed a globally-sharded batch:
params live replicated on every chip, the batch is split along the ``data``
mesh axis, and XLA inserts the gradient all-reduce (psum over ICI) during
compilation. Parameter averaging, gradient sharing, and the parameter server
are all THIS one mechanism — exact (no compression loss), synchronous, and
overlapped with backprop by the compiler.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.model import _iter_batches
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh


class ParallelWrapper:
    """Drop-in accelerator for a MultiLayerNetwork/ComputationGraph: same
    ``fit`` surface, batch sharded over the mesh's ``data`` axis.

    Usage::

        pw = ParallelWrapper(model)          # all local devices
        pw.fit((x, y), epochs=10, batch_size=512)

    The global batch must divide by the data-axis size (the reference
    round-robins whole DataSets to workers; here the sharding is exact).
    """

    def __init__(self, model, mesh: Optional[Mesh] = None):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh(MeshSpec())
        self.n_data = self.mesh.shape["data"]
        self._repl = NamedSharding(self.mesh, P())

    def _shard(self, arr):
        if arr is None:
            return None
        arr = jnp.asarray(arr, self.model.dtype)
        spec = P("data", *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def _replicate_model(self):
        put = lambda t: jax.device_put(t, self._repl)
        self.model.params = jax.tree_util.tree_map(put, self.model.params)
        self.model.state = jax.tree_util.tree_map(put, self.model.state)
        if self.model.opt_state is not None:
            self.model.opt_state = jax.tree_util.tree_map(put, self.model.opt_state)

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None):
        """Data-parallel fit: identical semantics to ``model.fit`` on a batch
        ``batch_size`` large, executed across all chips."""
        if self.model.params is None:
            self.model.init()
        self._replicate_model()
        model = self.model
        for _ in range(epochs):
            for l in model.listeners:
                l.on_epoch_start(model, model.epoch)
            source = data() if callable(data) else data
            for x, y, fm, lm in _iter_batches(source, batch_size):
                n = len(x)
                if n % self.n_data != 0:
                    # pad to a shardable batch (masked examples would be
                    # better; DL4J just sends uneven batches to workers)
                    pad = self.n_data - n % self.n_data
                    # tile so any n reaches the next multiple of n_data (a
                    # slice x[:pad] is short when pad > n)
                    def _pad(a):
                        a = np.asarray(a)
                        reps = np.concatenate([a] * (pad // n + 1))[:pad]
                        return np.concatenate([a, reps])

                    x = _pad(x)
                    if y is not None:
                        y = _pad(y)
                    if fm is not None:
                        fm = _pad(fm)
                    if lm is not None:
                        lm = _pad(lm)
                score = model._fit_batch(
                    self._shard(x), self._shard(y), self._shard(fm), self._shard(lm)
                )
                if model.listeners:
                    score = float(score)
                    for l in model.listeners:
                        l.iteration_done(model, model.iteration, score, n)
            for l in model.listeners:
                l.on_epoch_end(model, model.epoch)
            model.epoch += 1
        return model

    def output(self, x):
        """Sharded batched inference across the mesh."""
        return self.model.output(self._shard(np.asarray(x)))
