"""Parallelism: device meshes, data-parallel training, parallel inference.

TPU-native replacement for the reference's entire scaleout stack
(SURVEY.md §2.5): ParallelWrapper's averaging/gradient-sharing modes, both
Spark TrainingMasters, and the Aeron VoidParameterServer all collapse into
ONE mechanism — a jitted train step whose batch is sharded over a mesh axis
and whose gradients are all-reduced by XLA collectives over ICI (DCN across
slices). Threshold compression (EncodedGradientsAccumulator) and the
cross-replica sharded weight update are available as an OPT-IN explicit
exchange (parallel/grads.py, env DL4J_TPU_GRAD_COMPRESS /
DL4J_TPU_SHARDED_UPDATE): on a single ICI-connected slice the implicit dense
all-reduce is already optimal (SURVEY.md §5.8), but when the exchange
crosses DCN — multi-slice or Ethernet-attached hosts — the 16x ternary wire
format and the 1/R-per-replica optimizer math pay for themselves. Both
switches default OFF; see docs/PERF.md.
"""

from deeplearning4j_tpu.parallel.mesh import (
    MeshSpec, data_axis_size, data_sharded, make_mesh,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.context import current_mesh, use_mesh
from deeplearning4j_tpu.parallel.distributed import (
    global_array,
    init_distributed,
    is_multihost,
    replicate_global,
    shutdown_distributed,
)
from deeplearning4j_tpu.parallel.compress import (
    decode_gathered,
    encode_packed,
    pack_ternary,
    packed_nbytes,
    threshold_decode,
    threshold_encode,
    unpack_ternary,
)
from deeplearning4j_tpu.parallel.grads import DataParallelStep, GradExchange
from deeplearning4j_tpu.parallel.elastic import (
    ElasticRuntime,
    FileStore,
    Membership,
    MembershipChanged,
    View,
)
from deeplearning4j_tpu.parallel.gpipe import GPipeTrainer
from deeplearning4j_tpu.parallel.ring import local_attention, ring_self_attention
from deeplearning4j_tpu.parallel.pipeline import PipelineParallel, stack_stage_params
from deeplearning4j_tpu.parallel.tp import ShardedTrainer, tp_param_shardings
from deeplearning4j_tpu.parallel.mesh_step import MeshTrainer, shard_update_spec

__all__ = [
    "MeshSpec", "make_mesh", "ParallelWrapper", "ParallelInference",
    "current_mesh", "use_mesh", "local_attention", "ring_self_attention",
    "GPipeTrainer", "PipelineParallel", "stack_stage_params", "ShardedTrainer",
    "tp_param_shardings", "init_distributed", "shutdown_distributed",
    "is_multihost", "global_array", "replicate_global",
    "DataParallelStep", "GradExchange", "data_axis_size", "data_sharded",
    "ElasticRuntime", "FileStore", "Membership", "MembershipChanged", "View",
    "MeshTrainer", "shard_update_spec",
    "threshold_encode", "threshold_decode", "pack_ternary", "unpack_ternary",
    "encode_packed", "decode_gathered", "packed_nbytes",
]
