"""Parallelism: device meshes, data-parallel training, parallel inference.

TPU-native replacement for the reference's entire scaleout stack
(SURVEY.md §2.5): ParallelWrapper's averaging/gradient-sharing modes, both
Spark TrainingMasters, and the Aeron VoidParameterServer all collapse into
ONE mechanism — a jitted train step whose batch is sharded over a mesh axis
and whose gradients are all-reduced by XLA collectives over ICI (DCN across
slices). Threshold compression (EncodedGradientsAccumulator) is deliberately
absent: it existed because Ethernet was the bottleneck; ICI makes dense
bf16/f32 all-reduce cheaper than encode/decode (SURVEY.md §5.8).
"""

from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.context import current_mesh, use_mesh
from deeplearning4j_tpu.parallel.distributed import (
    global_array,
    init_distributed,
    is_multihost,
    replicate_global,
    shutdown_distributed,
)
from deeplearning4j_tpu.parallel.gpipe import GPipeTrainer
from deeplearning4j_tpu.parallel.ring import local_attention, ring_self_attention
from deeplearning4j_tpu.parallel.pipeline import PipelineParallel, stack_stage_params
from deeplearning4j_tpu.parallel.tp import ShardedTrainer, tp_param_shardings

__all__ = [
    "MeshSpec", "make_mesh", "ParallelWrapper", "ParallelInference",
    "current_mesh", "use_mesh", "local_attention", "ring_self_attention",
    "GPipeTrainer", "PipelineParallel", "stack_stage_params", "ShardedTrainer",
    "tp_param_shardings", "init_distributed", "shutdown_distributed",
    "is_multihost", "global_array", "replicate_global",
]
