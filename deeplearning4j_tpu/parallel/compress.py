"""Threshold gradient compression with residual error feedback.

Capability parity with ND4J's ``thresholdEncode``/``thresholdDecode``
(SURVEY §1 layer 1) — the mechanism behind the reference's
EncodedGradientsAccumulator / SharedTrainingMaster gradient sharing —
re-designed TPU-first: everything here is pure jax on fixed shapes, so the
encode → exchange → decode round-trip stays INSIDE the one compiled train
step (no host round-trip, no variable-length buffers), and the per-replica
residual rides in the donated step carry.

Scheme (1-bit / ternary quantization):

- ``threshold_encode``: accumulate the incoming gradient into the residual,
  emit ``sign(acc) * threshold`` wherever ``|acc| >= threshold`` and carry
  the remainder forward. The residual error feedback makes the scheme
  lossless over time: every gradient component is eventually transmitted
  (``sum(q_t) + r_T == sum(g_t) + r_0`` holds exactly as an algebraic
  invariant).
- ``pack_ternary`` / ``unpack_ternary``: 2 bits per element (codes 0/+1/-1
  packed 4-per-byte), a 16x wire-size reduction vs float32 gradients. The
  packed uint8 array is what crosses the interconnect (all-gather over the
  ``data`` axis — compressed payloads are not summable, so replicas exchange
  encodings and every replica decodes + sums deterministically, exactly like
  the reference's workers applying each other's encoded updates).

Everything is bitwise-deterministic: elementwise ops plus a fixed-order sum
over the replica axis, so identically-seeded runs produce identical params.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = [
    "decode_gathered",
    "encode_packed",
    "pack_ternary",
    "packed_nbytes",
    "threshold_decode",
    "threshold_encode",
    "unpack_ternary",
]

# 2 bits per element, 4 elements per packed byte.
_ELEMS_PER_BYTE = 4


def packed_nbytes(n: int) -> int:
    """Wire bytes for an ``n``-element ternary-packed gradient."""
    return (n + _ELEMS_PER_BYTE - 1) // _ELEMS_PER_BYTE


def threshold_encode(grad, residual, threshold) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize ``grad + residual`` to {-threshold, 0, +threshold}.

    Returns ``(q, new_residual)`` with ``q + new_residual == grad + residual``
    exactly — the error-feedback invariant that makes repeated encoding
    lossless over time (components below threshold accumulate until they
    cross it).
    """
    acc = grad + residual
    thr = jnp.asarray(threshold, acc.dtype)
    q = jnp.where(jnp.abs(acc) >= thr, jnp.sign(acc) * thr,
                  jnp.zeros_like(acc))
    return q, acc - q


def threshold_decode(q, target):
    """Apply an encoded update to ``target`` (ND4J thresholdDecode parity:
    decode accumulates the quantized update into the receiver's buffer)."""
    return target + q


def pack_ternary(signs) -> jnp.ndarray:
    """Pack a 1-D array of {-1, 0, +1} values into 2-bit codes, 4 per byte.

    Code map: 0 -> 0, +1 -> 1, -1 -> 2 (code 3 unused). Returns uint8 of
    ``packed_nbytes(n)`` bytes; trailing slots in the last byte are 0.
    """
    n = signs.shape[0]
    codes = ((signs > 0).astype(jnp.int32) + 2 * (signs < 0).astype(jnp.int32))
    pad = (-n) % _ELEMS_PER_BYTE
    if pad:
        codes = jnp.concatenate([codes, jnp.zeros((pad,), jnp.int32)])
    codes = codes.reshape(-1, _ELEMS_PER_BYTE)
    weights = jnp.asarray([1, 4, 16, 64], jnp.int32)
    return jnp.sum(codes * weights, axis=1).astype(jnp.uint8)


def unpack_ternary(packed, n: int) -> jnp.ndarray:
    """Inverse of :func:`pack_ternary`; accepts a leading batch axis (the
    all-gathered ``[R, nbytes]`` payload) and returns float32 signs
    ``[..., n]`` in {-1, 0, +1}."""
    b = packed.astype(jnp.int32)
    codes = jnp.stack([(b >> s) & 3 for s in (0, 2, 4, 6)], axis=-1)
    flat = codes.reshape(packed.shape[:-1] + (-1,))[..., :n]
    return (flat == 1).astype(jnp.float32) - (flat == 2).astype(jnp.float32)


def encode_packed(grad, residual, threshold):
    """One replica's wire payload: ``(packed_uint8, new_residual)``."""
    q, new_residual = threshold_encode(grad, residual, threshold)
    return pack_ternary(jnp.sign(q)), new_residual


def decode_gathered(gathered, n: int, threshold, dtype):
    """Decode the all-gathered ``[R, nbytes]`` payloads and sum over replicas.

    The sum runs in float32 in a fixed order (axis 0), then casts to the
    gradient dtype — deterministic on every backend.
    """
    signs = unpack_ternary(gathered, n)               # [R, n] float32
    total = signs.sum(axis=0) * jnp.asarray(threshold, jnp.float32)
    return total.astype(dtype)
