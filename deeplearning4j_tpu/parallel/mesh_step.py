"""The named-mesh step: first-class (data × tensor × stage) training.

The MULTICHIP dryrun proved a (d2,t2,s2) mesh runs; this module
productionizes it (ISSUE 13). ``MeshTrainer`` drives ONE jitted step
program (``nn/step_program.py``) over a named ``parallel/mesh.py`` mesh:

- **data** axis: the global batch shards over it (pure GSPMD data
  parallelism — XLA inserts the gradient all-reduce during compilation).
- **model** axis: Megatron tensor parallelism via the
  ``parallel/tp.py`` PartitionSpec rules (column/row-parallel projections;
  collectives inserted by GSPMD).
- **pipe** axis: inside the unified step the stage axis carries the
  **sharded weight update** (arXiv 2004.13336): optimizer moments — and
  with them the update math — shard over every spare mesh axis, so each
  device updates only ``1/(d·s)`` of each replicated parameter (GSPMD turns
  the gradient all-reduce into reduce-scatter + all-gather around the
  sharded update). Dedicated stage-COMPUTE composition (the micro-batch
  ring schedule) remains ``parallel/gpipe.py``, which instantiates the same
  step-program abstraction.

The mesh shape ``(d, t, s)`` is a tuned knob triple
(``mesh_data``/``mesh_model``/``mesh_pipe`` in ``tune/knobs.py``): with no
spec given the trainer applies the tuning DB (``tune.maybe_apply``) and
reads ``DL4J_TPU_MESH_*`` — the fit choke point for PR 9's
successive-halving search. Compressed gradient exchange (PR 3) composes on
the pure-data mesh via the explicit shard_map exchange
(``compress=True``); see docs/PARALLELISM.md for why the compressed DCN
tier and the in-jit GSPMD tiers are mutually exclusive per axis.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn.step_program import StepProgram, mesh_shape_from_env
from deeplearning4j_tpu.parallel.context import use_mesh
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.tp import tp_param_shardings

__all__ = ["MeshSlice", "MeshTrainer", "shard_update_spec"]


class MeshSlice:
    """One elastic member's device mesh in the elastic-of-slices
    composition (``train/elastic.py``): the member process IS a whole
    ``(d, t, s)`` slice, membership events happen per slice, and the
    member's local compute (the vshard backward pass) runs GSPMD-sharded
    over the slice's devices — batch over ``data``, params/state
    replicated, XLA inserting the in-slice collectives. The fleet-level
    exchange above stays explicit store payloads; preempting the slice
    kills this one process.

    ``spec`` is ``"d[,t[,s]]"`` (e.g. ``"2"``, ``"2,1,1"``). Bit-exactness
    of elastic runs holds across member COUNT at a fixed slice shape — the
    in-slice reduction order is the mesh's, so reference and chaos runs
    must use the same spec.
    """

    def __init__(self, spec, devices=None):
        d, t, s = self.parse_spec(spec)
        self.spec = MeshSpec(data=d, model=t, pipe=s)
        self.mesh = make_mesh(self.spec, list(devices)
                              if devices is not None else jax.devices())
        self.data = int(self.mesh.shape["data"])

    @staticmethod
    def parse_spec(spec) -> Tuple[int, int, int]:
        if isinstance(spec, (tuple, list)):
            parts = [int(v) for v in spec]
        else:
            parts = [int(v) for v in str(spec).split(",") if v.strip()]
        if not parts or len(parts) > 3 or any(v < 1 for v in parts):
            raise ValueError(
                f"slice spec {spec!r}: want 1-3 positive ints 'd[,t[,s]]'")
        return tuple(parts + [1] * (3 - len(parts)))  # type: ignore

    def round_rows(self, rows: int) -> int:
        """Smallest multiple of the data-axis size >= ``rows`` (vshard
        micro-batches must divide evenly over the batch sharding)."""
        return -(-int(rows) // self.data) * self.data

    def shard_batch(self, arr):
        """Place a leading-batch-dim array sharded over ``data``."""
        if arr is None:
            return None
        spec = P("data", *([None] * (np.ndim(arr) - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def replicate(self, tree):
        """Place a pytree fully replicated on the slice."""
        repl = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), tree)

    def run(self, fn, *args, **kwargs):
        """Call ``fn`` under this slice's mesh context (GSPMD partitions
        the jitted computation by the inputs' shardings)."""
        with use_mesh(self.mesh):
            return fn(*args, **kwargs)


def shard_update_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh,
                      axes: Tuple[str, ...] = ("data", "pipe")) -> P:
    """Extend a (possibly empty) TP PartitionSpec with the cross-replica
    weight-update sharding of arXiv 2004.13336: the first dimension the TP
    rules left unsharded and whose size divides evenly shards over the spare
    mesh axes — jointly when possible (``P(("data","pipe"))``), then over
    each alone, else the leaf stays as the TP rules had it. Memory math
    (docs/PARALLELISM.md): adam moments drop from 2·N·4 bytes per device to
    ``2·N·4/(d·s)``; GSPMD rewrites the gradient all-reduce into
    reduce-scatter + sharded update + all-gather, which on a ring moves the
    same bytes as the all-reduce it replaces."""
    if not shape:
        return spec
    dims = list(spec) + [None] * (len(shape) - len(spec))
    live = [a for a in axes if mesh.shape.get(a, 1) > 1]
    for combo in (tuple(live),) + tuple((a,) for a in live):
        if not combo:
            continue
        n = int(np.prod([mesh.shape[a] for a in combo]))
        if n <= 1:
            continue
        for i, d in enumerate(dims):
            if d is None and shape[i] % n == 0 and shape[i] >= n:
                dims[i] = combo if len(combo) > 1 else combo[0]
                return P(*dims)
    return spec


class MeshTrainer:
    """Train a MultiLayerNetwork on a named (data × model × pipe) mesh with
    ONE step program: params per TP rules, batch over ``data``, optimizer
    state and the weight update sharded over every spare axis.

    ``spec=None`` resolves the mesh shape from the ``DL4J_TPU_MESH_*``
    knobs (after applying the tuning DB when ``DL4J_TPU_TUNE`` is set) —
    unset knobs mean pure data parallelism over all devices.

    ``compress=True`` routes through the explicit shard_map exchange
    (``parallel/grads.py``) with PR 3 gradient compression — only legal on
    a pure-data mesh: the compressed wire format packs per-replica flat
    shards, which has no tensor/stage decomposition.
    """

    def __init__(self, model, spec: Optional[MeshSpec] = None, *,
                 devices=None, compress: bool = False):
        import os as _os

        self.model = model
        devices = list(devices) if devices is not None else jax.devices()
        if spec is None:
            if _os.environ.get("DL4J_TPU_TUNE"):
                # fit choke point for the mesh knobs: the persisted tuner
                # winner lands in DL4J_TPU_MESH_* BEFORE the shape is read
                from deeplearning4j_tpu import tune as _tune

                _tune.maybe_apply(model, "fit")
            d, t, s = mesh_shape_from_env(len(devices))
            spec = MeshSpec(data=d, model=t, pipe=s)
        self.spec = spec
        self.mesh = make_mesh(spec, devices)
        self.shape = tuple(spec.resolve(len(devices)))  # (d, t, s_seq, p)
        if model.params is None:
            model.init()
        if compress:
            d, t, _, p = self.shape
            if t > 1 or p > 1:
                raise ValueError(
                    "compressed exchange needs a pure data mesh (t=s=1): "
                    "the packed wire format has no tensor/stage "
                    f"decomposition — got (d={d}, t={t}, s={p})")
            from deeplearning4j_tpu.parallel.grads import DataParallelStep

            self._dp = DataParallelStep(model, self.mesh, compress=True)
        else:
            self._dp = None
            self._param_shardings = tp_param_shardings(model, self.mesh)
            self._opt_shardings = self._make_opt_shardings()
            self._place()
        self._step: Optional[StepProgram] = None

    # -- placement ---------------------------------------------------------
    def _extend(self, spec: P, a) -> NamedSharding:
        return NamedSharding(
            self.mesh, shard_update_spec(spec, np.shape(a), self.mesh))

    def _make_opt_shardings(self):
        """Optimizer-state shardings: moment trees mirror their params' TP
        spec, extended along the spare (data/pipe) axes; structure-mismatch
        slots (scalar counters, stateless updaters) extend from
        replicated."""
        m = self.model
        out = []
        for opt_layer, shard_layer in zip(m.opt_state, self._param_shardings):
            if not isinstance(opt_layer, dict):
                out.append(jax.tree_util.tree_map(
                    lambda a: self._extend(P(), a), opt_layer))
                continue
            placed = {}
            for slot, tree in opt_layer.items():
                try:
                    placed[slot] = jax.tree_util.tree_map(
                        lambda a, s: self._extend(s.spec, a),
                        tree, shard_layer)
                except ValueError:
                    placed[slot] = jax.tree_util.tree_map(
                        lambda a: self._extend(P(), a), tree)
            out.append(placed)
        return tuple(out)

    def _place(self):
        m = self.mesh
        model = self.model
        model.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s),
            model.params, self._param_shardings,
            is_leaf=lambda x: isinstance(x, jax.Array))
        repl = NamedSharding(m, P())
        model.state = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), model.state)
        model.opt_state = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s),
            model.opt_state, self._opt_shardings)
        # cached step/output executables were traced without the mesh
        model._step_fn = model._tbptt_step_fn = model._output_fn = None

    # -- the one jitted program --------------------------------------------
    def _constrain(self, tree, stree):
        return jax.tree_util.tree_map(
            lambda a, s: jax.lax.with_sharding_constraint(a, s), tree, stree)

    def _build_step(self) -> StepProgram:
        body = self.model._step_body(False)
        p_shard = self._param_shardings
        o_shard = self._opt_shardings

        def wrap_body(step):
            def mesh_step(params, opt_state, state, it, rng, x, y, fm, lm,
                          carries, ex_weight=None):
                p, o, s, c, loss = step(params, opt_state, state, it, rng,
                                        x, y, fm, lm, carries,
                                        ex_weight=ex_weight)
                # pin the 2004.13336 layout: new moments stay sharded over
                # every spare axis (GSPMD reduce-scatters the grads into the
                # sharded update), new params land back on the TP layout
                # (the all-gather half) — outputs then match the donated
                # inputs' shardings, so steady-state dispatch never re-lands
                # buffers and never recompiles
                p = self._constrain(p, p_shard)
                o = self._constrain(o, o_shard)
                return p, o, s, c, loss

            return mesh_step

        return StepProgram(body, "mesh.step", model=self.model,
                           wrap_body=wrap_body, hits_site="mesh.fit")

    def _get_step(self) -> StepProgram:
        if self._step is None:
            self._step = self._build_step()
        return self._step

    # -- dispatch ----------------------------------------------------------
    def _shard_batch(self, arr):
        if arr is None:
            return None
        from deeplearning4j_tpu.nn.model import _cast_input

        arr = _cast_input(arr, self.model.dtype)
        d = self.mesh.shape["data"]
        if arr.shape[0] % d:
            raise ValueError(
                f"batch rows {arr.shape[0]} must divide the data axis ({d})")
        spec = P("data", *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def fit_batch(self, x, y, fm=None, lm=None, ew=None):
        """One mesh step; returns the loss (device scalar)."""
        if self._dp is not None:
            return self._dp.fit_batch(x, y, fm, lm, ew=ew)
        from deeplearning4j_tpu.nn.model import _cast_labels

        model = self.model
        step = self._get_step()
        x = self._shard_batch(x)
        y = self._shard_batch(_cast_labels(y, model.dtype))
        fm = self._shard_batch(fm)
        lm = self._shard_batch(lm)
        ew = self._shard_batch(ew)
        with use_mesh(self.mesh), obs.span("mesh.step"):
            (model.params, model.opt_state, model.state, _,
             loss) = step.dispatch(
                model.params, model.opt_state, model.state,
                jnp.asarray(model.iteration, jnp.int32), model._next_rng(),
                x, y, fm, lm, (), ex_weight=ew)
        model.iteration += 1
        return loss

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None):
        from deeplearning4j_tpu.nn.model import _iter_batches

        model = self.model
        for _ in range(epochs):
            source = data() if callable(data) else data
            for xb, yb, fmb, lmb in _iter_batches(source, batch_size):
                score = self.fit_batch(xb, yb, fmb, lmb)
                if model.listeners:
                    # listeners consume host floats (same contract as
                    # model.fit: sync only when someone reads the score)
                    score = float(score)  # graftlint: disable=host-sync
                    for l in model.listeners:
                        l.iteration_done(model, model.iteration, score,
                                         len(xb))
            model.epoch += 1
        return model

    def output(self, x):
        with use_mesh(self.mesh):
            return self.model.output(self._shard_batch(x))

    def finish(self):
        """Leave mesh layout: gather params/opt/state back to replicated so
        the model serializes and runs single-chip as usual. (TP/update
        shardings are a placement, not a format — one device_put undoes
        them.) The compressed-exchange variant delegates to the shard_map
        runner's own finish."""
        if self._dp is not None:
            self._dp.finish()
            return
        model = self.model
        repl = NamedSharding(self.mesh, P())
        for attr in ("params", "opt_state", "state"):
            setattr(model, attr, jax.tree_util.tree_map(
                lambda a: jax.device_put(a, repl), getattr(model, attr)))
        model._step_fn = model._tbptt_step_fn = model._output_fn = None
        self._step = None
