"""GPipe pipeline parallelism as a FRAMEWORK feature: train any (stateless)
MultiLayerConfiguration pipelined over the mesh's ``pipe`` axis.

Beyond-reference capability (SURVEY.md §2.5 — the reference is data-parallel
only). ``parallel/pipeline.py`` holds the low-level SPMD ring kernel; this
module makes it a first-class trainer:

- **Auto-partitioning**: the resolved layer list (preprocessors included) is
  split into ``pipe``-many CONTIGUOUS stages balanced by parameter count.
- **Heterogeneous stages in one SPMD program**: per-stage parameter pytrees
  are raveled to f32 vectors, zero-padded to the longest stage, and stacked
  [S, Lmax] — an ordinary array sharded P('pipe'). Each rank recovers ITS
  stage's tree with a static unravel inside ``lax.switch(rank, branches)``;
  XLA's conditional executes only the taken branch per device.
- **Unequal boundary widths**: inter-stage activations are flattened to
  [mb, Fmax] (max boundary width) with exact zero-pad on exit and slice +
  reshape on entry — no lossy projection, so GPipe training is numerically
  EQUIVALENT to single-device training (test_gpipe.py asserts parameter
  equality against plain MultiLayerNetwork.fit).
- **Real updater stack**: the configuration's updater (sgd/adam/rmsprop/...)
  runs on the stacked vectors + loss head — elementwise transforms are
  invariant to the ravel, so updates match the per-layer single-device math.
- **Listeners** fire per iteration like MultiLayerNetwork.fit.
- ``to_model()`` unravels the trained vectors back into an ordinary
  MultiLayerNetwork for inference/serialization/evaluation.

v2 additions:

- **BatchNorm**: train-mode normalization uses per-microbatch statistics
  (standard GPipe semantics); with a data axis > 1 the normalization unit
  is the per-device microbatch SHARD (no cross-shard sync-BN — collectives
  cannot live inside the rank switch). Each stage emits its BN layers'
  batch stats as a fixed-width [all means | all variances] aux vector per
  microbatch; across data shards the variances combine with the stable
  parallel-variance form (no E[x^2]-mean^2 cancellation), and the step
  chains the running-stat EMA over microbatches in order. With data=1 and
  n_micro=1 the trainer is EXACTLY the single-device full-batch step, BN
  included; with data=1, n_micro>1 it matches a single-device run that
  microbatches the same way — both asserted in test_gpipe.py.
- **Dropout and weight noise**: per-(microbatch, layer) keys derived as
  ``fold_in(fold_in(base_rng, micro), global_layer_index)`` (weight noise
  additionally fold_in(., 0x5EED), exactly like MultiLayerNetwork._forward)
  — a scheme a single-device reference reproduces exactly.
- **Per-layer updater overrides**: supported when the override is the
  same updater TYPE differing only in lr (incl. trainable=False == lr 0):
  every updater here is linear in lr with internally-consistent state, so
  a per-position scale vector on the stacked update is exact. Different
  types / non-lr field diffs stay rejected.
- **Per-stage rematerialization** (jax.checkpoint on every stage branch):
  the classic GPipe activation-memory optimization.

Round-5 additions (closing VERDICT r4 #5/#8):

- **Token-id pipelines**: an EmbeddingSequence first layer makes stage 0's
  ring input the raw [B, T] id array (exact in the f32 buffers, never cast
  to a lossy model dtype) — the TransformerLM flagship pipelines.
- **PP x TP composition** (``tp_axis``): the loss head computes OUTSIDE the
  rank switch in shared code, so its (vocab-sized) projection shards
  column-parallel over an ordinary GSPMD axis.
- **Gradient normalization + constraints**: applied per layer on the
  replicated stacked vectors via unravel → per-layer op → re-ravel
  (`_map_stage_layers`) — exact, because grads/params there equal the
  single-device trees.
- **Feature/label masks**: per-stage boundary masks (propagated once,
  statically checked shape-preserving) enter the switch as one
  [S, M, mb, W] operand; each branch threads its slice through its layers;
  the head scores with the label mask or the propagated feature mask.

v2 limitations (explicit, checked): non-BN stateful layers are rejected;
masks require a recurrent [B, T] layout whose mask stays shape-preserving
through every layer — the DP/TP paths cover the rest.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.model import MultiLayerNetwork, _iter_batches
from deeplearning4j_tpu.parallel.ring import shard_map
from deeplearning4j_tpu.train.updaters import make_updater


def partition_layers(param_counts: Sequence[int], n_stages: int) -> List[Tuple[int, int]]:
    """Contiguous [start, end) ranges balanced by parameter count (greedy
    prefix split at target boundaries; every stage non-empty)."""
    n = len(param_counts)
    if n_stages > n:
        raise ValueError(f"{n_stages} stages for {n} layers")
    total = float(sum(param_counts)) or 1.0
    bounds = [0]
    acc = 0.0
    for i, c in enumerate(param_counts):
        acc += c
        # must leave enough layers for the remaining stages
        remaining_needed = n_stages - len(bounds)
        if len(bounds) < n_stages and acc >= total * len(bounds) / n_stages \
                and i + 1 <= n - remaining_needed:
            bounds.append(i + 1)
    while len(bounds) < n_stages:
        bounds.append(min(bounds[-1] + 1, n - (n_stages - len(bounds))))
    bounds.append(n)
    return [(bounds[i], bounds[i + 1]) for i in range(n_stages)]


class GPipeTrainer:
    """Pipeline-parallel trainer for a MultiLayerConfiguration.

    Usage::

        mesh = make_mesh(MeshSpec(data=2, pipe=2))
        tr = GPipeTrainer(conf, mesh, n_micro=4)
        tr.fit((x, y), epochs=3)
        model = tr.to_model()     # ordinary MultiLayerNetwork
    """

    def __init__(self, conf, mesh: Mesh, n_micro: int = 2,
                 pipe_axis: str = "pipe", data_axis: str = "data",
                 tp_axis: Optional[str] = None):
        self.conf = conf
        self.mesh = mesh
        self.n_micro = n_micro
        self.pipe_axis = pipe_axis
        self.data_axis = data_axis
        # PP x TP composition: the loss head (usually the vocab-sized
        # projection, the single largest matmul in an LM) runs OUTSIDE the
        # rank switch in shared post-pipeline code, so ordinary GSPMD
        # tensor parallelism applies there: shard its 2-D weights
        # column-parallel over ``tp_axis`` and XLA inserts the collectives.
        # (In-stage TP would need collectives inside lax.switch, which the
        # pipelined program cannot express — see module docstring.)
        self.tp_axis = tp_axis
        self.n_stages = mesh.shape[pipe_axis]
        if self.n_stages < 2:
            raise ValueError("GPipeTrainer needs a pipe axis of size >= 2")

        # Resolve via an ordinary network (preprocessors, n_in inference,
        # initial params) — single source of truth for layer semantics.
        self._ref = MultiLayerNetwork(conf).init()
        self._validate()

        body = list(range(len(self._ref.layers) - 1))   # loss head excluded
        self.head_idx = len(self._ref.layers) - 1
        self.head_cfg = self._ref.layers[self.head_idx]
        counts = [
            sum(int(np.prod(np.shape(l)))
                for l in jax.tree_util.tree_leaves(self._ref.params[i]))
            for i in body
        ]
        self.stage_ranges = partition_layers(counts, self.n_stages)

        self._build_stages()
        self.updater = make_updater(conf.updater)
        self._update_scales = self._build_update_scales()
        self.opt_state = self.updater.init((self.stacked, self.head_params))
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self._step = None
        self._rng = jax.random.PRNGKey((conf.seed or 0) + 7919)

    def _build_update_scales(self):
        """Per-position lr scale [S, Lmax] for the stacked update + scalar
        head scale. Per-layer overrides must be the conf updater's TYPE
        differing only in lr; trainable=False scales to 0."""
        from deeplearning4j_tpu.train.updaters import normalize_updater

        base = dict(normalize_updater(self.conf.updater))
        base_lr = float(base.get("lr", 0.0)) or 1.0

        def layer_scale(layer) -> float:
            if not getattr(layer, "trainable", True):
                return 0.0
            ov = getattr(layer, "updater", None)
            if ov is None:
                return 1.0
            spec = dict(normalize_updater(ov))
            if spec.get("type") != base.get("type"):
                raise NotImplementedError(
                    "GPipeTrainer v2: per-layer updater override of a "
                    f"DIFFERENT type ({spec.get('type')} vs "
                    f"{base.get('type')}) is unsupported")
            rest_a = {k: v for k, v in spec.items() if k != "lr"}
            rest_b = {k: v for k, v in base.items() if k != "lr"}
            if rest_a != rest_b:
                raise NotImplementedError(
                    "GPipeTrainer v2: per-layer updater overrides may only "
                    "differ in lr")
            if base.get("type") == "adadelta":
                return 1.0  # adadelta has no lr
            return float(spec.get("lr", base_lr)) / base_lr

        scale = np.ones(self.stacked.shape, np.float32)
        for si, (s, e) in enumerate(self.stage_ranges):
            off = 0
            for gi in range(s, e):
                n = sum(int(np.prod(np.shape(l))) for l in
                        jax.tree_util.tree_leaves(self._ref.params[gi]))
                scale[si, off:off + n] = layer_scale(self._ref.layers[gi])
                off += n
        return jnp.asarray(scale), jnp.float32(layer_scale(self.head_cfg))

    # -- validation --------------------------------------------------------
    def _validate(self):
        from deeplearning4j_tpu.nn.layers import BatchNorm

        for i, layer in enumerate(self._ref.layers):
            name = type(layer).__name__
            if jax.tree_util.tree_leaves(self._ref.state[i]) and \
                    not isinstance(layer, BatchNorm):
                raise NotImplementedError(
                    f"GPipeTrainer v2: layer {i} ({name}) carries non-BN "
                    "running state — use DP/TP for such nets")

    # -- stage construction ------------------------------------------------
    def _build_stages(self):
        ref = self._ref
        mb_shapes = []       # static input shape (sans batch) per stage
        self._stage_layers = []
        vecs, unravels, self._stage_lens = [], [], []

        from deeplearning4j_tpu.nn.layers.core import EmbeddingSequence

        for (s, e) in self.stage_ranges:
            stage_params = tuple(ref.params[i] for i in range(s, e))
            vec, unravel = ravel_pytree(stage_params)
            vec = jnp.asarray(vec, jnp.float32)
            vecs.append(vec)
            unravels.append(unravel)
            self._stage_lens.append(vec.size)
            self._stage_layers.append(tuple(ref.layers[i] for i in range(s, e)))
            if s == 0 and isinstance(ref.layers[0], EmbeddingSequence):
                # token-id input: the real array is [B, T] integer ids, not
                # the [B, T, vocab] the recurrent InputType describes (ids
                # ride the f32 ring buffers exactly — vocab < 2^24)
                mb_shapes.append((ref.layer_input_types[0].timesteps,))
            else:
                mb_shapes.append(ref.layer_input_types[s].batch_shape(1)[1:])

        out_shape = ref.layer_input_types[self.head_idx].batch_shape(1)[1:]
        self._boundary_shapes = mb_shapes + [out_shape]
        flat_sizes = [int(np.prod(s)) for s in self._boundary_shapes]
        self.f_max = max(flat_sizes)
        self._in_shapes = mb_shapes
        self._in_sizes = flat_sizes[:-1]
        self.out_size = flat_sizes[-1]
        self.out_shape = out_shape

        l_max = max(self._stage_lens)
        self.stacked = jnp.stack([
            jnp.pad(v, (0, l_max - v.size)) for v in vecs
        ])  # [S, Lmax]
        self.stacked = jax.device_put(
            self.stacked, NamedSharding(self.mesh, P(self.pipe_axis)))
        self._unravels = unravels
        if self.tp_axis and self.mesh.shape.get(self.tp_axis, 1) > 1:
            # column-parallel head: 2-D weights sharded on the OUTPUT dim,
            # 1-D biases alike — GSPMD partitions the head matmul + loss
            def head_spec(a):
                n_tp = self.mesh.shape[self.tp_axis]
                if np.ndim(a) == 2 and np.shape(a)[1] % n_tp == 0:
                    return NamedSharding(self.mesh, P(None, self.tp_axis))
                if np.ndim(a) == 1 and np.shape(a)[0] % n_tp == 0:
                    return NamedSharding(self.mesh, P(self.tp_axis))
                return NamedSharding(self.mesh, P())

            self.head_params = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, head_spec(a)),
                ref.params[self.head_idx])
        else:
            self.head_params = jax.device_put(
                ref.params[self.head_idx],
                NamedSharding(self.mesh, P()))

        # BN metadata per stage: (local pos, global layer idx, n_features,
        # decay, feature offset). The aux vector is laid out as TWO halves,
        # [all means | all variances]: a layout that is uniform across
        # ranks, so the cross-data-shard variance combine (the stable
        # parallel form, not E[x^2]-mean^2 cancellation) can run in shared
        # post-switch code.
        from deeplearning4j_tpu.nn.layers import BatchNorm

        self._stage_bn = []
        feat_widths = []
        for si, (s, e) in enumerate(self.stage_ranges):
            bns = []
            off = 0
            for lp, gi in enumerate(range(s, e)):
                layer = ref.layers[gi]
                if isinstance(layer, BatchNorm):
                    n = int(np.shape(ref.state[gi]["mean"])[0])
                    bns.append((lp, gi, n, float(layer.decay), off))
                    off += n
            self._stage_bn.append(bns)
            feat_widths.append(off)
        self.a_half = max(1, max(feat_widths) if feat_widths else 1)
        self.a_max = 2 * self.a_half
        # running stats, replicated (tiny [C] vectors), keyed by layer idx
        self.bn_state = {
            gi: {k: jnp.asarray(v, jnp.float32)
                 for k, v in ref.state[gi].items()}
            for bns in self._stage_bn for (_lp, gi, _n, _d, _off) in bns
        }

        # per-stage branch: [Lmax], [mb, Fmax], micro, rng
        #   -> ([mb, Fmax], [A_max])
        def make_branch(i):
            unravel = unravels[i]
            layers = self._stage_layers[i]
            in_size, in_shape = self._in_sizes[i], self._in_shapes[i]
            length = self._stage_lens[i]
            s0 = self.stage_ranges[i][0]
            # token-id stage input stays f32 (exact for vocab < 2^24): a
            # bf16 model-dtype cast would corrupt ids > 256
            is_ids = (i == 0 and isinstance(ref.layers[0], EmbeddingSequence))
            bn_at = {lp: (n, decay, off)
                     for (lp, _gi, n, decay, off) in self._stage_bn[i]}

            def branch(vec, xf, micro, rng, masks=None):
                params = unravel(vec[:length])
                x = xf[:, :in_size].reshape((xf.shape[0],) + tuple(in_shape))
                if not is_ids:
                    x = x.astype(self._ref.dtype)
                m = None
                if masks is not None and self._mask_meta and \
                        self._mask_meta[1][s0]:
                    # this stage's input mask for THIS microbatch (masks is
                    # the full [S, M, mb, W] stack — identical operand to
                    # every switch branch; each uses only its own row)
                    m = lax.dynamic_index_in_dim(
                        masks[i], micro, 0, keepdims=False)
                    m = m.astype(self._ref.dtype)
                aux = jnp.zeros((self.a_max,), jnp.float32)
                kmicro = jax.random.fold_in(rng, micro)
                for lp, (layer, p) in enumerate(zip(layers, params)):
                    # per-(micro, GLOBAL layer) key — reproducible by a
                    # single-device microbatched reference
                    lrng = jax.random.fold_in(kmicro, s0 + lp)
                    if layer.weight_noise:
                        # same keying as MultiLayerNetwork._forward
                        p = layer.maybe_weight_noise(
                            p, True, jax.random.fold_in(lrng, 0x5EED))
                    if lp in bn_at:
                        n, decay, off = bn_at[lp]
                        zero = {"mean": jnp.zeros((n,), jnp.float32),
                                "var": jnp.zeros((n,), jnp.float32)}
                        x, ns = layer.apply(p, zero, x, train=True, rng=lrng,
                                            mask=m)
                        # state was 0 => ns = (1-decay) * batch_stat
                        bmean = ns["mean"] / (1.0 - decay)
                        bvar = ns["var"] / (1.0 - decay)
                        aux = lax.dynamic_update_slice(
                            aux, lax.stop_gradient(bmean.astype(jnp.float32)),
                            (off,))
                        aux = lax.dynamic_update_slice(
                            aux, lax.stop_gradient(bvar.astype(jnp.float32)),
                            (self.a_half + off,))
                    else:
                        x, _ = layer.apply(p, self._ref.state[s0 + lp], x,
                                           train=True, rng=lrng, mask=m)
                    if m is not None:
                        m = layer.propagate_mask(
                            m, self._ref.layer_input_types[s0 + lp])
                out = x.reshape(x.shape[0], -1).astype(jnp.float32)
                pad = self.f_max - out.shape[1]
                out = jnp.pad(out, ((0, 0), (0, pad))) if pad else out
                # zero-valued but structurally REAL dependence on the rng
                # (and mask stack): branches must all consume the same
                # inputs or lax.switch's partial-eval produces mismatched
                # residual sets under grad (stages without dropout/masks
                # would otherwise DCE the operand)
                out = out + 0.0 * jax.random.uniform(
                    kmicro, (), dtype=out.dtype)
                if masks is not None:
                    out = out + 0.0 * masks.ravel()[0].astype(out.dtype)
                return out, aux

            return branch

        self._branches = [make_branch(i) for i in range(self.n_stages)]
        self._mask_meta = self._build_mask_meta()

    def _build_mask_meta(self):
        """Static mask topology for the pipelined mask channel: per-layer
        input-mask aliveness, decided ONCE by propagating a dummy [1, W]
        mask through the resolved layer list. Returns (W, alive[list]) for
        [B, T]-shaped recurrent feature masks, or None when this net can't
        take masks (non-recurrent input, or a layer that reshapes its
        mask — those nets use DP/TP)."""
        it0 = self.conf.input_type
        if getattr(it0, "kind", None) != "recurrent" or not it0.timesteps:
            return None
        W = int(it0.timesteps)
        m = jnp.ones((1, W), jnp.float32)
        alive = []
        for layer, it in zip(self._ref.layers, self._ref.layer_input_types):
            alive.append(m is not None)
            if m is not None:
                m = layer.propagate_mask(m, it)
                if m is not None:
                    if tuple(np.shape(m)) != (1, W):
                        return None  # mask-reshaping layer: unsupported
        return W, alive

    def _boundary_masks(self, fm):
        """Propagate the real [B, W] feature mask to every stage boundary
        plus the head input. Returns ([S, B, W] f32, head_mask or None)."""
        W, alive = self._mask_meta
        per_stage = []
        m = jnp.asarray(fm, jnp.float32)
        gi = 0
        for si, (s, e) in enumerate(self.stage_ranges):
            while gi < s:
                if m is not None:
                    m = self._ref.layers[gi].propagate_mask(
                        m, self._ref.layer_input_types[gi])
                gi += 1
            per_stage.append(m if m is not None else jnp.zeros(fm.shape, jnp.float32))
        while gi < self.head_idx:
            if m is not None:
                m = self._ref.layers[gi].propagate_mask(
                    m, self._ref.layer_input_types[gi])
            gi += 1
        return jnp.stack(per_stage), m

    # -- the SPMD pipelined step ------------------------------------------
    def _pipelined_forward(self, stacked, x_micro, rng, masks_all=None):
        """GPipe ring (the shared ``pipeline._gpipe_shard`` kernel) with a
        per-(stage, micro) aux channel: at step t each rank applies its
        stage and also emits its BN layers' batch stats. Returns
        (outs [M, mb, Fmax], aux [S, M, A_max]). ``masks_all``: optional
        [S, M, mb, W] per-stage-boundary feature masks (the mask channel —
        replicated across pipe, data-sharded on mb)."""
        from deeplearning4j_tpu.parallel.pipeline import _gpipe_shard

        branches = self._branches
        axis_name = self.pipe_axis
        data_axis = self.data_axis
        half = self.a_half

        def aux_combine(aux):
            # Cross-data-shard combine of the [means | local vars] halves,
            # OUTSIDE the rank switch (collectives inside a data-dependent
            # branch would not be statically matched across devices). The
            # parallel-variance form is numerically stable — no
            # E[x^2]-mean^2 cancellation (shards are equal-sized, so plain
            # pmeans are exact).
            mu = aux[:half]
            var_loc = aux[half:]
            mu_g = lax.pmean(mu, data_axis)
            var_g = (lax.pmean(var_loc, data_axis)
                     + lax.pmean((mu - mu_g) ** 2, data_axis))
            return jnp.concatenate([mu_g, var_g])

        def make_shard_fn(with_masks: bool):
            def shard_fn(params_local, x_mic, rng_, masks_=None):
                def _pvary(x):
                    try:
                        return lax.pcast(x, axis_name, to="varying")
                    except ValueError:  # already varying over the pipe axis
                        return x
                    except (AttributeError, TypeError):
                        pass
                    try:
                        return lax.pvary(x, axis_name)  # jax ~0.5/0.6
                    except AttributeError:
                        # jax 0.4.x: no varying-axis aval types to cast
                        return x

                # Each branch is rematerialized (jax.checkpoint): classic
                # GPipe per-stage activation recomputation, AND it makes
                # every branch's autodiff residuals = its inputs — identical
                # avals across branches, which lax.switch's partial-eval
                # requires (branches that differ in rng/dropout usage
                # otherwise produce unequal residual sets with mismatched
                # device-varying types). Outputs are normalized to
                # pipe-varying for the same reason.
                rng_v = jax.tree_util.tree_map(_pvary, rng_)
                extra = (_pvary(masks_),) if with_masks else ()
                wrapped = [
                    jax.checkpoint(lambda v, xx, mm, *rest, _b=b: tuple(
                        _pvary(o) for o in _b(v, xx, mm, *rest)))
                    for b in branches
                ]

                def stage_apply(params, x, micro):
                    idx = lax.axis_index(axis_name)
                    # every arm is collective-free (stage layers; outputs
                    # pvary-normalized) and check_vma stays on below
                    return lax.switch(idx, wrapped, params, x, micro,  # graftlint: disable=collective-consistency
                                      rng_v, *extra)

                return _gpipe_shard(
                    params_local, _pvary(x_mic), stage_apply=stage_apply,
                    axis_name=axis_name, n_stages=self.n_stages,
                    aux_width=self.a_max, aux_combine=aux_combine)
            return shard_fn

        xspec = P(None, self.data_axis)
        if masks_all is not None:
            in_specs = (P(self.pipe_axis), xspec, P(),
                        P(None, None, self.data_axis, None))
            fn, args = make_shard_fn(True), (stacked, x_micro, rng, masks_all)
        else:
            in_specs = (P(self.pipe_axis), xspec, P())
            fn, args = make_shard_fn(False), (stacked, x_micro, rng)
        out_specs = (xspec, P(self.pipe_axis))
        # NOTE: check_vma must stay ON here — _gpipe_shard's psum/ppermute
        # ring depends on the varying-axes machinery. Pallas kernels (whose
        # outputs carry no vma) therefore cannot run inside stages: the
        # fused-LSTM dispatch is suppressed at trace time (see
        # no_fused_lstm in fit_batch / nn/layers/recurrent.py).
        try:
            return shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs)(*args)
        except Exception as e:  # noqa: BLE001 — jax raises bare Exception here
            # jax 0.4.x has no pvary, so the lax.switch branches cannot be
            # unified under its replication checker ("mismatched replication
            # types"). The check is static-only; disabling it keeps the
            # psum/ppermute ring semantics intact on 0.4.x.
            if "replication" not in str(e) and "check_rep" not in str(e):
                raise
            try:
                return shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)(*args)
            except TypeError:
                raise e

    def _loss(self, params, x_micro, y_micro, rng, masks_all=None,
              head_mask=None):
        stacked, head = params
        outs, aux = self._pipelined_forward(stacked, x_micro, rng, masks_all)
        M, mb = outs.shape[0], outs.shape[1]
        pre = outs[:, :, :self.out_size].reshape(
            (M * mb,) + tuple(self.out_shape)).astype(self._ref.dtype)
        y = y_micro.reshape((M * mb,) + tuple(y_micro.shape[2:]))
        total = self.head_cfg.score(head, pre, y, mask=head_mask, average=True)
        # l1/l2 penalties, computed on the (replicated) stacked vectors —
        # same terms the single-device step adds
        for si in range(self.n_stages):
            tree = self._unravels[si](stacked[si, :self._stage_lens[si]])
            for layer, p in zip(self._stage_layers[si], tree):
                total = total + layer.regularization_penalty(p)
        return total + self.head_cfg.regularization_penalty(head), aux

    def _chain_bn_states(self, bn_state, aux):
        """EMA-chain each BN layer's running stats over the microbatches in
        order: s_{m+1} = d*s_m + (1-d)*batch_m (exactly what a
        single-device microbatched run produces). aux rows are laid out as
        [all means | all variances] halves (data-axis-aggregated via the
        stable parallel-variance combine)."""
        M = aux.shape[1]
        half = self.a_half
        new_state = {}
        for si, bns in enumerate(self._stage_bn):
            for (_lp, gi, n, decay, off) in bns:
                mean = bn_state[gi]["mean"]
                var = bn_state[gi]["var"]
                for m in range(M):
                    bm = aux[si, m, off:off + n]
                    bv = aux[si, m, half + off:half + off + n]
                    mean = decay * mean + (1.0 - decay) * bm
                    var = decay * var + (1.0 - decay) * bv
                new_state[gi] = {"mean": mean, "var": var}
        return new_state

    def _map_stage_layers(self, stacked_vecs, fn):
        """Unravel each stage row, apply ``fn(global_idx, layer, tree) ->
        tree`` per layer, re-ravel. Runs inside the jitted step on the
        replicated [S, Lmax] vectors (cheap elementwise/norm math) — the
        channel that makes per-layer gradient normalization and post-update
        constraints EXACT under pipelining."""
        rows = []
        for si in range(self.n_stages):
            tree = list(self._unravels[si](
                stacked_vecs[si, :self._stage_lens[si]]))
            s, e = self.stage_ranges[si]
            changed = False
            for off, gi in enumerate(range(s, e)):
                new = fn(gi, self._ref.layers[gi], tree[off])
                if new is not tree[off]:
                    tree[off] = new
                    changed = True
            if not changed:
                rows.append(stacked_vecs[si])
                continue
            vec, _ = ravel_pytree(tuple(tree))
            vec = jnp.asarray(vec, jnp.float32)
            rows.append(jnp.pad(vec, (0, stacked_vecs.shape[1] - vec.size)))
        return jnp.stack(rows)

    def make_train_step(self):
        from deeplearning4j_tpu.nn.constraints import apply_constraints
        from deeplearning4j_tpu.train.updaters import (
            apply_gradient_normalization)

        updater = self.updater
        scale, head_scale = self._update_scales
        has_gn = any(getattr(l, "gradient_normalization", None)
                     for l in self._ref.layers)
        has_cn = any(getattr(l, "constraints", None) for l in self._ref.layers)

        def norm_grads(grads):
            sg, hg = grads

            def norm_one(_gi, layer, g_tree):
                gn = getattr(layer, "gradient_normalization", None)
                if not gn or not jax.tree_util.tree_leaves(g_tree):
                    return g_tree
                return apply_gradient_normalization(
                    gn, getattr(layer, "gradient_normalization_threshold", 1.0),
                    g_tree)

            sg = self._map_stage_layers(sg, norm_one)
            gn = getattr(self.head_cfg, "gradient_normalization", None)
            if gn:
                hg = apply_gradient_normalization(
                    gn, getattr(self.head_cfg,
                                "gradient_normalization_threshold", 1.0), hg)
            return sg, hg

        def constrain(params):
            stacked, head = params

            def con_one(_gi, layer, p_tree):
                if not getattr(layer, "constraints", None) or \
                        not jax.tree_util.tree_leaves(p_tree):
                    return p_tree
                return apply_constraints(layer, p_tree)

            stacked = self._map_stage_layers(stacked, con_one)
            if getattr(self.head_cfg, "constraints", None):
                head = apply_constraints(self.head_cfg, head)
            return stacked, head

        def apply_update(params, opt_state, bn_state, it, loss, aux, grads):
            upd, new_opt = updater.update(grads, opt_state, params, it)
            su, hu = upd
            # per-position lr scale (per-layer overrides / frozen layers);
            # exact because every updater here is linear in lr with
            # internally-consistent state (see module docstring)
            su = su * scale
            hu = jax.tree_util.tree_map(lambda d: d * head_scale, hu)
            stacked, head = params
            new_params = (stacked - su,
                          jax.tree_util.tree_map(lambda p, d: p - d, head, hu))
            if has_cn:
                new_params = constrain(new_params)
            new_bn = self._chain_bn_states(bn_state, aux)
            return new_params, new_opt, new_bn, loss

        def step(params, opt_state, bn_state, it, x_micro, y_micro, rng,
                 masks_all=None, head_mask=None):
            (loss, aux), grads = jax.value_and_grad(
                self._loss, has_aux=True)(params, x_micro, y_micro, rng,
                                          masks_all, head_mask)
            return apply_update(params, opt_state, bn_state, it, loss, aux,
                                grads)

        from deeplearning4j_tpu.nn.step_program import StepProgram

        if not has_gn:
            # aot_wrap=False: the gpipe stage-switched executable is built
            # per trainer and warmed by its first dispatch (no bucket ladder
            # over [S, M, mb, W] stacks); StepProgram still owns the
            # donate/trace policy and the cost-exemplar harvest
            return StepProgram(step, "gpipe.step", aot_wrap=False)

        # Gradient normalization must NOT run inside a jitted executable
        # that also sees the pipe-sharded state: the GSPMD partitioner
        # resolves the nonlinear clip/renorm intermediate inconsistently
        # between its consumers — the norm is taken over the per-replica
        # value while the downstream subtraction consumes a spuriously
        # all-reduced copy, scaling the applied update by exactly the
        # data*seq replica count (observed 4x on a data=2 x seq=2 mesh).
        # Sharding constraints, optimization barriers, and materializing
        # the gradients at a jit boundary all fail to stop it; only fully
        # replicated operands compile correctly, which would defeat the
        # pipe-sharded parameter layout. So the clip math runs EAGERLY on
        # the [S, Lmax] stage vectors between the two executables — a few
        # tiny elementwise/norm dispatches per step, only for gn-bearing
        # configs — and the (linear-in-grads) updater half stays jitted.
        # (standalone repro: tools/repro_gpipe_clip_miscompile.py; tracked
        # in docs/TEST_DEBT.md — retire this split once a fixed XLA lands)
        grads_jit = StepProgram(
            lambda params, x_micro, y_micro, rng, masks_all=None,
            head_mask=None: jax.value_and_grad(self._loss, has_aux=True)(
                params, x_micro, y_micro, rng, masks_all, head_mask),
            "gpipe.grads", donate_argnums=(), aot_wrap=False)
        update_jit = StepProgram(apply_update, "gpipe.update", aot_wrap=False)

        def split_step(params, opt_state, bn_state, it, x_micro, y_micro,
                       rng, masks_all=None, head_mask=None):
            (loss, aux), grads = grads_jit(params, x_micro, y_micro, rng,
                                           masks_all, head_mask)
            grads = norm_grads(grads)  # eager: see partitioner note above
            return update_jit(params, opt_state, bn_state, it, loss, aux,
                              grads)

        return split_step

    # -- training API ------------------------------------------------------
    def fit_batch(self, x, y, fm=None, lm=None):
        x, y = np.asarray(x), np.asarray(y)
        B = x.shape[0]
        if B % self.n_micro:
            raise ValueError(
                f"batch size {B} must be divisible by n_micro={self.n_micro}")
        mb = B // self.n_micro
        n_data = self.mesh.shape[self.data_axis]
        if mb % n_data:
            raise ValueError(
                f"microbatch size {mb} (= {B}/{self.n_micro}) must be "
                f"divisible by the '{self.data_axis}' mesh axis ({n_data})")
        xm = jnp.asarray(x.reshape((self.n_micro, mb) + x.shape[1:]), jnp.float32)
        # ring buffers carry FLAT activations: flatten+pad input to Fmax
        xm = xm.reshape(self.n_micro, mb, -1)
        pad = self.f_max - xm.shape[-1]
        if pad:
            xm = jnp.pad(xm, ((0, 0), (0, 0), (0, pad)))
        ym = jnp.asarray(y.reshape((self.n_micro, mb) + y.shape[1:]))
        self._rng, k = jax.random.split(self._rng)
        from deeplearning4j_tpu.nn.layers.recurrent import no_fused_lstm

        args = ((self.stacked, self.head_params), self.opt_state,
                self.bn_state, jnp.asarray(self.iteration, jnp.int32),
                xm, ym, k)
        if fm is None and lm is None:
            if self._step is None:
                self._step = self.make_train_step()
            with no_fused_lstm():   # stage switch can't host pallas (vma)
                out = self._step(*args)
        else:
            # mask channel (round 5): per-stage boundary masks ride into
            # the switch as one [S, M, mb, W] stack; the head scores with
            # the label mask (preferred) or the propagated feature mask
            if self._mask_meta is None:
                raise NotImplementedError(
                    "GPipeTrainer masks need a recurrent [B, T] input whose "
                    "mask keeps its shape through every layer — use DP/TP "
                    "for other mask layouts")
            if fm is not None:
                per_stage, head_m = self._boundary_masks(jnp.asarray(fm))
                masks_all = per_stage.reshape(
                    (self.n_stages, self.n_micro, mb, per_stage.shape[-1]))
            else:
                # label-mask-only: no feature-mask channel needed — the
                # single-device step likewise only scores the head with lm
                masks_all, head_m = None, None
            head_mask = jnp.asarray(lm) if lm is not None else head_m
            key = (masks_all is not None, head_mask is not None)
            if getattr(self, "_step_m", None) is None:
                self._step_m = {}
            if key not in self._step_m:
                self._step_m[key] = self.make_train_step()
            with no_fused_lstm():   # stage switch can't host pallas (vma)
                out = self._step_m[key](*args, masks_all, head_mask)
        ((self.stacked, self.head_params), self.opt_state, self.bn_state,
         loss) = out
        self.iteration += 1
        return loss

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None):
        for _ in range(epochs):
            for l in self.listeners:
                l.on_epoch_start(self, self.epoch)
            source = data() if callable(data) else data
            for x, y, fm, lm in _iter_batches(source, batch_size):
                loss = self.fit_batch(x, y, fm, lm)
                if self.listeners:
                    loss = float(loss)
                    for l in self.listeners:
                        l.iteration_done(self, self.iteration, loss, len(x))
            for l in self.listeners:
                l.on_epoch_end(self, self.epoch)
            self.epoch += 1
        return self

    def set_listeners(self, *ls):
        self.listeners = list(ls)
        return self

    # -- back to an ordinary model ----------------------------------------
    def to_model(self) -> MultiLayerNetwork:
        """Unravel the trained stage vectors into a plain MultiLayerNetwork
        (params host-local, ready for output/evaluate/serialization)."""
        model = MultiLayerNetwork(self.conf).init()
        stacked = np.asarray(jax.device_get(self.stacked))
        new_params = list(model.params)
        for si, (s, e) in enumerate(self.stage_ranges):
            tree = self._unravels[si](
                jnp.asarray(stacked[si, :self._stage_lens[si]]))
            for off, i in enumerate(range(s, e)):
                new_params[i] = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a, model.dtype), tree[off])
        new_params[self.head_idx] = jax.tree_util.tree_map(
            lambda a: jnp.asarray(jax.device_get(a), model.dtype),
            self.head_params)
        model.params = tuple(new_params)
        new_state = list(model.state)
        for gi, st in self.bn_state.items():
            new_state[gi] = {k: jnp.asarray(jax.device_get(v), jnp.float32)
                             for k, v in st.items()}
        model.state = tuple(new_state)
        model.iteration = self.iteration
        model.epoch = self.epoch
        return model
