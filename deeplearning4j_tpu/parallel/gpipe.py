"""GPipe pipeline parallelism as a FRAMEWORK feature: train any (stateless)
MultiLayerConfiguration pipelined over the mesh's ``pipe`` axis.

Beyond-reference capability (SURVEY.md §2.5 — the reference is data-parallel
only). ``parallel/pipeline.py`` holds the low-level SPMD ring kernel; this
module makes it a first-class trainer:

- **Auto-partitioning**: the resolved layer list (preprocessors included) is
  split into ``pipe``-many CONTIGUOUS stages balanced by parameter count.
- **Heterogeneous stages in one SPMD program**: per-stage parameter pytrees
  are raveled to f32 vectors, zero-padded to the longest stage, and stacked
  [S, Lmax] — an ordinary array sharded P('pipe'). Each rank recovers ITS
  stage's tree with a static unravel inside ``lax.switch(rank, branches)``;
  XLA's conditional executes only the taken branch per device.
- **Unequal boundary widths**: inter-stage activations are flattened to
  [mb, Fmax] (max boundary width) with exact zero-pad on exit and slice +
  reshape on entry — no lossy projection, so GPipe training is numerically
  EQUIVALENT to single-device training (test_gpipe.py asserts parameter
  equality against plain MultiLayerNetwork.fit).
- **Real updater stack**: the configuration's updater (sgd/adam/rmsprop/...)
  runs on the stacked vectors + loss head — elementwise transforms are
  invariant to the ravel, so updates match the per-layer single-device math.
- **Listeners** fire per iteration like MultiLayerNetwork.fit.
- ``to_model()`` unravels the trained vectors back into an ordinary
  MultiLayerNetwork for inference/serialization/evaluation.

v1 limitations (explicit, checked): layers with running state (BatchNorm) or
rng needs (dropout), per-layer updater overrides, gradient normalization,
constraints, and masks are rejected with clear errors — the DP/TP paths
cover those; this trainer targets the deep feed-forward/conv stacks where
pipeline memory scaling matters.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.model import MultiLayerNetwork, _iter_batches
from deeplearning4j_tpu.parallel.ring import shard_map
from deeplearning4j_tpu.train.updaters import make_updater


def partition_layers(param_counts: Sequence[int], n_stages: int) -> List[Tuple[int, int]]:
    """Contiguous [start, end) ranges balanced by parameter count (greedy
    prefix split at target boundaries; every stage non-empty)."""
    n = len(param_counts)
    if n_stages > n:
        raise ValueError(f"{n_stages} stages for {n} layers")
    total = float(sum(param_counts)) or 1.0
    bounds = [0]
    acc = 0.0
    for i, c in enumerate(param_counts):
        acc += c
        # must leave enough layers for the remaining stages
        remaining_needed = n_stages - len(bounds)
        if len(bounds) < n_stages and acc >= total * len(bounds) / n_stages \
                and i + 1 <= n - remaining_needed:
            bounds.append(i + 1)
    while len(bounds) < n_stages:
        bounds.append(min(bounds[-1] + 1, n - (n_stages - len(bounds))))
    bounds.append(n)
    return [(bounds[i], bounds[i + 1]) for i in range(n_stages)]


class GPipeTrainer:
    """Pipeline-parallel trainer for a MultiLayerConfiguration.

    Usage::

        mesh = make_mesh(MeshSpec(data=2, pipe=2))
        tr = GPipeTrainer(conf, mesh, n_micro=4)
        tr.fit((x, y), epochs=3)
        model = tr.to_model()     # ordinary MultiLayerNetwork
    """

    def __init__(self, conf, mesh: Mesh, n_micro: int = 2,
                 pipe_axis: str = "pipe", data_axis: str = "data"):
        self.conf = conf
        self.mesh = mesh
        self.n_micro = n_micro
        self.pipe_axis = pipe_axis
        self.data_axis = data_axis
        self.n_stages = mesh.shape[pipe_axis]
        if self.n_stages < 2:
            raise ValueError("GPipeTrainer needs a pipe axis of size >= 2")

        # Resolve via an ordinary network (preprocessors, n_in inference,
        # initial params) — single source of truth for layer semantics.
        self._ref = MultiLayerNetwork(conf).init()
        self._validate()

        body = list(range(len(self._ref.layers) - 1))   # loss head excluded
        self.head_idx = len(self._ref.layers) - 1
        self.head_cfg = self._ref.layers[self.head_idx]
        counts = [
            sum(int(np.prod(np.shape(l)))
                for l in jax.tree_util.tree_leaves(self._ref.params[i]))
            for i in body
        ]
        self.stage_ranges = partition_layers(counts, self.n_stages)

        self._build_stages()
        self.updater = make_updater(conf.updater)
        self.opt_state = self.updater.init((self.stacked, self.head_params))
        self.iteration = 0
        self.epoch = 0
        self.listeners: list = []
        self._step = None

    # -- validation --------------------------------------------------------
    def _validate(self):
        for i, layer in enumerate(self._ref.layers):
            name = type(layer).__name__
            if jax.tree_util.tree_leaves(self._ref.state[i]):
                raise NotImplementedError(
                    f"GPipeTrainer v1: layer {i} ({name}) carries running "
                    "state (BatchNorm?) — use DP/TP for stateful nets")
            if getattr(layer, "dropout", 0.0):
                raise NotImplementedError(
                    f"GPipeTrainer v1: layer {i} ({name}) uses dropout (rng "
                    "plumbing through the pipe ring is not implemented)")
            if getattr(layer, "updater", None) is not None:
                raise NotImplementedError(
                    "GPipeTrainer v1: per-layer updater overrides unsupported")
            if getattr(layer, "gradient_normalization", None) or \
                    getattr(layer, "constraints", None):
                raise NotImplementedError(
                    "GPipeTrainer v1: gradient normalization / constraints "
                    "unsupported")

    # -- stage construction ------------------------------------------------
    def _build_stages(self):
        ref = self._ref
        mb_shapes = []       # static input shape (sans batch) per stage
        self._stage_layers = []
        vecs, unravels, self._stage_lens = [], [], []

        for (s, e) in self.stage_ranges:
            stage_params = tuple(ref.params[i] for i in range(s, e))
            vec, unravel = ravel_pytree(stage_params)
            vec = jnp.asarray(vec, jnp.float32)
            vecs.append(vec)
            unravels.append(unravel)
            self._stage_lens.append(vec.size)
            self._stage_layers.append(tuple(ref.layers[i] for i in range(s, e)))
            mb_shapes.append(ref.layer_input_types[s].batch_shape(1)[1:])

        out_shape = ref.layer_input_types[self.head_idx].batch_shape(1)[1:]
        self._boundary_shapes = mb_shapes + [out_shape]
        flat_sizes = [int(np.prod(s)) for s in self._boundary_shapes]
        self.f_max = max(flat_sizes)
        self._in_shapes = mb_shapes
        self._in_sizes = flat_sizes[:-1]
        self.out_size = flat_sizes[-1]
        self.out_shape = out_shape

        l_max = max(self._stage_lens)
        self.stacked = jnp.stack([
            jnp.pad(v, (0, l_max - v.size)) for v in vecs
        ])  # [S, Lmax]
        self.stacked = jax.device_put(
            self.stacked, NamedSharding(self.mesh, P(self.pipe_axis)))
        self._unravels = unravels
        self.head_params = jax.device_put(
            ref.params[self.head_idx],
            NamedSharding(self.mesh, P()))

        # per-stage branch: [Lmax], [mb, Fmax] -> [mb, Fmax]
        def make_branch(i):
            unravel = unravels[i]
            layers = self._stage_layers[i]
            in_size, in_shape = self._in_sizes[i], self._in_shapes[i]
            length = self._stage_lens[i]

            def branch(vec, xf):
                params = unravel(vec[:length])
                x = xf[:, :in_size].reshape((xf.shape[0],) + tuple(in_shape))
                x = x.astype(self._ref.dtype)
                for layer, p in zip(layers, params):
                    x, _ = layer.apply(p, {}, x, train=True, rng=None)
                out = x.reshape(x.shape[0], -1).astype(jnp.float32)
                pad = self.f_max - out.shape[1]
                return jnp.pad(out, ((0, 0), (0, pad))) if pad else out

            return branch

        self._branches = [make_branch(i) for i in range(self.n_stages)]

    # -- the SPMD pipelined step ------------------------------------------
    def _stage_apply(self, vec, x, rank):
        return lax.switch(rank, self._branches, vec, x)

    def _pipelined_forward(self, stacked, x_micro):
        # Same ring schedule as the low-level kernel (pipeline._gpipe_shard);
        # only the stage body differs — the rank-switched heterogeneous
        # branch dispatch.
        from deeplearning4j_tpu.parallel.pipeline import _gpipe_shard

        fn = functools.partial(
            _gpipe_shard,
            stage_apply=lambda vec, x: self._stage_apply(
                vec, x, lax.axis_index(self.pipe_axis)),
            axis_name=self.pipe_axis,
            n_stages=self.n_stages,
        )
        xspec = P(None, self.data_axis)
        return shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(P(self.pipe_axis), xspec),
            out_specs=xspec,
        )(stacked, x_micro)

    def _loss(self, params, x_micro, y_micro):
        stacked, head = params
        outs = self._pipelined_forward(stacked, x_micro)   # [M, mb, Fmax]
        M, mb = outs.shape[0], outs.shape[1]
        pre = outs[:, :, :self.out_size].reshape(
            (M * mb,) + tuple(self.out_shape)).astype(self._ref.dtype)
        y = y_micro.reshape((M * mb,) + tuple(y_micro.shape[2:]))
        total = self.head_cfg.score(head, pre, y, mask=None, average=True)
        # l1/l2 penalties, computed on the (replicated) stacked vectors —
        # same terms the single-device step adds
        for si in range(self.n_stages):
            tree = self._unravels[si](stacked[si, :self._stage_lens[si]])
            for layer, p in zip(self._stage_layers[si], tree):
                total = total + layer.regularization_penalty(p)
        return total + self.head_cfg.regularization_penalty(head)

    def make_train_step(self):
        updater = self.updater

        def step(params, opt_state, it, x_micro, y_micro):
            loss, grads = jax.value_and_grad(self._loss)(params, x_micro, y_micro)
            upd, new_opt = updater.update(grads, opt_state, params, it)
            new_params = jax.tree_util.tree_map(lambda p, d: p - d, params, upd)
            return new_params, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1))

    # -- training API ------------------------------------------------------
    def fit_batch(self, x, y):
        if self._step is None:
            self._step = self.make_train_step()
        x, y = np.asarray(x), np.asarray(y)
        B = x.shape[0]
        if B % self.n_micro:
            raise ValueError(
                f"batch size {B} must be divisible by n_micro={self.n_micro}")
        mb = B // self.n_micro
        n_data = self.mesh.shape[self.data_axis]
        if mb % n_data:
            raise ValueError(
                f"microbatch size {mb} (= {B}/{self.n_micro}) must be "
                f"divisible by the '{self.data_axis}' mesh axis ({n_data})")
        xm = jnp.asarray(x.reshape((self.n_micro, mb) + x.shape[1:]), jnp.float32)
        # ring buffers carry FLAT activations: flatten+pad input to Fmax
        xm = xm.reshape(self.n_micro, mb, -1)
        pad = self.f_max - xm.shape[-1]
        if pad:
            xm = jnp.pad(xm, ((0, 0), (0, 0), (0, pad)))
        ym = jnp.asarray(y.reshape((self.n_micro, mb) + y.shape[1:]))
        (self.stacked, self.head_params), self.opt_state, loss = self._step(
            (self.stacked, self.head_params), self.opt_state,
            jnp.asarray(self.iteration, jnp.int32), xm, ym)
        self.iteration += 1
        return loss

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None):
        for _ in range(epochs):
            for l in self.listeners:
                l.on_epoch_start(self, self.epoch)
            source = data() if callable(data) else data
            for x, y, fm, lm in _iter_batches(source, batch_size):
                if fm is not None or lm is not None:
                    raise NotImplementedError("GPipeTrainer v1: masks unsupported")
                loss = self.fit_batch(x, y)
                if self.listeners:
                    loss = float(loss)
                    for l in self.listeners:
                        l.iteration_done(self, self.iteration, loss, len(x))
            for l in self.listeners:
                l.on_epoch_end(self, self.epoch)
            self.epoch += 1
        return self

    def set_listeners(self, *ls):
        self.listeners = list(ls)
        return self

    # -- back to an ordinary model ----------------------------------------
    def to_model(self) -> MultiLayerNetwork:
        """Unravel the trained stage vectors into a plain MultiLayerNetwork
        (params host-local, ready for output/evaluate/serialization)."""
        model = MultiLayerNetwork(self.conf).init()
        stacked = np.asarray(jax.device_get(self.stacked))
        new_params = list(model.params)
        for si, (s, e) in enumerate(self.stage_ranges):
            tree = self._unravels[si](
                jnp.asarray(stacked[si, :self._stage_lens[si]]))
            for off, i in enumerate(range(s, e)):
                new_params[i] = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a, model.dtype), tree[off])
        new_params[self.head_idx] = jax.tree_util.tree_map(
            lambda a: jnp.asarray(jax.device_get(a), model.dtype),
            self.head_params)
        model.params = tuple(new_params)
        model.iteration = self.iteration
        model.epoch = self.epoch
        return model
