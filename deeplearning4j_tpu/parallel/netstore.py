"""Network-backed coordination store: the fleet half of ``parallel/elastic``.

A shared filesystem is an honest DCN stand-in on localhost and in CI, but a
real TPU fleet has none — membership and payload exchange ride a
coordination service (etcd in GKE fleets, Aeron in the reference stack).
This module is that service as a stdlib-only TCP key-value pair:

- :class:`NetStoreServer` — a threaded TCP server holding framed records in
  memory (optionally mirrored onto a :class:`~.elastic.FileStore` directory
  so a server restart loses nothing), with three etcd-shaped semantics on
  top of plain put/get:

  * **lease** — ``set(key, data, ttl=...)`` records expire ``ttl`` seconds
    after their last write; a heartbeat is just a renewing ``set``.
  * **CAS** — every key carries a version (count of successful writes);
    ``cas(key, data, version)`` writes only when the version still matches,
    and ``set_exclusive`` is CAS-from-absent (version 0): exactly one of any
    number of concurrent creators wins.
  * **watch** — ``watch(prefix, token)`` long-polls server-side until a key
    under ``prefix`` changes past the revision ``token``, replacing tight
    client poll loops with one blocked RPC.

- :class:`NetStore` — the client, exposing the exact ``FileStore`` surface
  (``set/set_exclusive/get/exists/delete/prune/list/*_json``) plus
  ``cas``/``version``/``watch``, so ``Membership``/``ElasticRuntime``/
  ``ElasticTrainer`` run unmodified against either backend. Payloads keep
  the same ``DLES`` CRC framing **end-to-end**: the client frames on write
  and validates on read, so a corrupt blob (bit-rot on the wire or in the
  server's memory/disk) counts and reads as missing — never as junk.

Connection loss is retried with bounded exponential backoff and fails fast
after ``fail_after`` seconds (default: the elastic lease TTL — once the
store has been unreachable that long the group has expelled us anyway, so
dying and rejoining beats hanging). Each thread gets its own socket: the
heartbeat daemon, watch long-polls, and payload prefetchers never serialize
behind one another.

Select a backend with ``DL4J_TPU_STORE`` (``tcp://host:port`` or
``file:/path``; a bare path is a FileStore) or :func:`open_store`.

Observability: ``dl4j_store_rpc_total{op,backend}`` /
``dl4j_store_rpc_retries_total`` counters, ``dl4j_store_watch_wait_seconds``
histogram, ``store_reconnect`` events (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import struct
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.parallel.elastic import (
    FileStore,
    _HEADER,
    _MAGIC,
    elastic_knobs,
)

__all__ = [
    "NetStore",
    "NetStoreServer",
    "StoreUnavailable",
    "open_store",
    "store_from_env",
]


_WIRE = struct.Struct("<I")        # length of the JSON header that follows
_MAX_HEADER = 1 << 20
_MAX_PAYLOAD = 1 << 31
_CHANGE_LOG = 4096                 # retained (rev, key) entries for watch


class StoreUnavailable(ConnectionError):
    """The store server stayed unreachable past the retry deadline. Subclass
    of ConnectionError/OSError so existing heartbeat/except-OSError paths
    degrade the same way they do for a briefly unwritable FileStore."""


# ---------------------------------------------------------------------------
# wire helpers (shared by server and client)
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    h = json.dumps(header, sort_keys=True).encode("utf-8")
    sock.sendall(_WIRE.pack(len(h)) + h + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("store connection closed mid-message")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    hlen = _WIRE.unpack(_recv_exact(sock, _WIRE.size))[0]
    if hlen > _MAX_HEADER:
        raise ConnectionError(f"store header of {hlen} bytes exceeds limit")
    header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    nbytes = int(header.get("nbytes", 0))
    if not 0 <= nbytes < _MAX_PAYLOAD:
        raise ConnectionError(f"store payload of {nbytes} bytes out of range")
    payload = _recv_exact(sock, nbytes) if nbytes else b""
    return header, payload


def _under(key: str, prefix: str) -> bool:
    return not prefix or key == prefix or key.startswith(prefix + "/")


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Record:
    __slots__ = ("data", "ver", "rev", "expire")

    def __init__(self, data: bytes, ver: int, rev: int,
                 expire: Optional[float]):
        self.data = data      # the client-framed blob, stored opaque
        self.ver = ver        # per-key write count (CAS token)
        self.rev = rev        # global revision at last write (watch token)
        self.expire = expire  # wall-clock lease deadline, None = no TTL


class NetStoreServer:
    """Threaded TCP KV server. One handler thread per connection; all state
    behind one lock + condition (watch wakeups). ``data_dir`` mirrors every
    record onto a FileStore so a restarted server resumes with its keys
    (versions restart at 1 and the revision at the key count — stale CAS and
    watch tokens from before the restart are rejected/treated as changed)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir=None):
        self._host = host
        self._port = int(port)
        self._kv: Dict[str, _Record] = {}
        self._rev = 0
        self._cond = threading.Condition()
        self._log: List[Tuple[int, str]] = []  # (rev, key) ring for watch
        self._disk = FileStore(data_dir) if data_dir else None
        self._srv: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        if self._disk is not None:
            self._load_disk()

    # -- persistence --------------------------------------------------------
    def _load_disk(self) -> None:
        root = self._disk.root
        # Boot-epoch skew: revisions restart ABOVE anything the previous
        # incarnation could have handed out, so a stale watch token always
        # reads as rev < self._rev with an empty change log -> "changed",
        # and a client re-syncs instead of blocking across the restart.
        boot = self._disk.get_json("__meta__/boot") or {}
        epoch = int(boot.get("epoch", 0)) + 1
        self._disk.set_json("__meta__/boot", {"epoch": epoch})
        self._rev = epoch << 32
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                if name.endswith(".tmp") or ".tmp." in name:
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), root)
                key = rel.replace(os.sep, "/")
                if key.startswith("__meta__/"):
                    continue
                data = self._disk.get(key)
                if data is None:
                    continue
                self._rev += 1
                self._kv[key] = _Record(data, 1, self._rev, None)

    # -- state transitions (all under self._cond) ---------------------------
    def _live(self, rec: Optional[_Record]) -> bool:
        if rec is None:
            return False
        return rec.expire is None or time.time() < rec.expire  # graftlint: disable=monotonic-clock

    def _bump(self, key: str) -> int:
        self._rev += 1
        self._log.append((self._rev, key))
        if len(self._log) > _CHANGE_LOG:
            del self._log[:len(self._log) - _CHANGE_LOG]
        return self._rev

    def _write(self, key: str, data: bytes, ver: int,
               ttl: Optional[float]) -> _Record:
        expire = (time.time() + float(ttl)) if ttl else None  # graftlint: disable=monotonic-clock
        rec = _Record(data, ver, self._bump(key), expire)
        self._kv[key] = rec
        if self._disk is not None:
            try:
                self._disk.set(key, data)
            except OSError:
                pass  # memory copy stays authoritative for this process
        self._cond.notify_all()
        return rec

    def _drop(self, key: str) -> None:
        if self._kv.pop(key, None) is not None:
            self._bump(key)
            if self._disk is not None:
                try:
                    self._disk.delete(key)
                except OSError:
                    pass
            self._cond.notify_all()

    # -- request dispatch ---------------------------------------------------
    def _handle(self, req: dict, payload: bytes) -> Tuple[dict, bytes]:
        op = req.get("op")
        key = str(req.get("key", ""))
        ttl = req.get("ttl")
        with self._cond:
            if op == "ping":
                return {"ok": True, "rev": self._rev}, b""
            if op == "set":
                rec = self._kv.get(key)
                ver = (rec.ver if self._live(rec) else 0) + 1
                rec = self._write(key, payload, ver, ttl)
                return {"ok": True, "ver": rec.ver, "rev": rec.rev}, b""
            if op == "setx":
                rec = self._kv.get(key)
                if self._live(rec):
                    return {"ok": False, "ver": rec.ver}, b""
                rec = self._write(key, payload, 1, ttl)
                return {"ok": True, "ver": rec.ver, "rev": rec.rev}, b""
            if op == "cas":
                want = int(req.get("ver", 0))
                rec = self._kv.get(key)
                have = rec.ver if self._live(rec) else 0
                if have != want:
                    return {"ok": False, "ver": have}, b""
                rec = self._write(key, payload, have + 1, ttl)
                return {"ok": True, "ver": rec.ver, "rev": rec.rev}, b""
            if op == "get":
                rec = self._kv.get(key)
                if not self._live(rec):
                    return {"exists": False}, b""
                return {"exists": True, "ver": rec.ver,
                        "nbytes": len(rec.data)}, rec.data
            if op == "exists":
                return {"exists": self._live(self._kv.get(key))}, b""
            if op == "ver":
                rec = self._kv.get(key)
                return {"ver": rec.ver if self._live(rec) else 0}, b""
            if op == "delete":
                self._drop(key)
                return {"ok": True}, b""
            if op == "prune":
                for k in [k for k in self._kv if _under(k, key)]:
                    self._drop(k)
                return {"ok": True}, b""
            if op == "list":
                head = (key + "/") if key else ""
                names = set()
                for k, rec in self._kv.items():
                    if k.startswith(head) and self._live(rec):
                        names.add(k[len(head):].split("/", 1)[0])
                return {"names": sorted(names)}, b""
            if op == "watch":
                return self._watch(key, int(req.get("since", 0)),
                                   float(req.get("timeout", 1.0))), b""
        return {"error": f"unknown op {op!r}"}, b""

    def _watch(self, prefix: str, since: int, timeout: float) -> dict:
        # Called with self._cond held. A ``since`` past the current revision
        # (a token from a previous server incarnation) reads as changed so
        # the client re-syncs instead of blocking forever.
        deadline = time.monotonic() + max(0.0, min(timeout, 60.0))
        if since > self._rev:
            return {"rev": self._rev, "changed": True}
        while True:
            if since < self._rev and (not self._log
                                      or self._log[0][0] > since + 1):
                # a revision gap the log cannot account for (ring truncation
                # or a server restart): assume changed
                return {"rev": self._rev, "changed": True}
            for rev, key in self._log:
                if rev > since and _under(key, prefix):
                    return {"rev": self._rev, "changed": True}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"rev": max(self._rev, since), "changed": False}
            self._cond.wait(remaining)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> Tuple[str, int]:
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    while True:
                        req, payload = _recv_msg(sock)
                        if outer._stopped:
                            break  # in-process stop(): act dead to clients
                        resp, data = outer._handle(req, payload)
                        if data:
                            resp = dict(resp, nbytes=len(data))
                        _send_msg(sock, resp, data)
                except (ConnectionError, OSError, ValueError):
                    pass  # client went away / spoke garbage: drop the conn

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((self._host, self._port), Handler)
        self._host, self._port = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="netstore-server",
            kwargs={"poll_interval": 0.05}, daemon=True)
        self._thread.start()
        return self._host, self._port

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    @property
    def port(self) -> int:
        return self._port

    def stop(self) -> None:
        self._stopped = True
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._cond:
            self._cond.notify_all()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class NetStore:
    """FileStore-interface client for :class:`NetStoreServer`.

    Every payload is DLES-framed on write and CRC-validated on read (the
    FileStore corrupt-blob-drop contract, end to end over the wire). RPCs
    retry with bounded exponential backoff on connection errors and raise
    :class:`StoreUnavailable` once the server has been unreachable for
    ``fail_after`` seconds (default: the elastic lease TTL). Sockets are
    per-thread, so a blocked watch long-poll never starves the heartbeat."""

    backend = "tcp"

    def __init__(self, address, *, timeout: float = 10.0,
                 fail_after: Optional[float] = None,
                 retry_base: float = 0.05):
        if isinstance(address, str):
            addr = address[6:] if address.startswith("tcp://") else address
            host, _, port = addr.rpartition(":")
            self.host, self.port = (host or "127.0.0.1"), int(port)
        else:
            self.host, self.port = address[0], int(address[1])
        self.timeout = float(timeout)
        self.fail_after = float(elastic_knobs()["ttl_s"]
                                if fail_after is None else fail_after)
        self.retry_base = float(retry_base)
        self._tls = threading.local()
        self._closed = False

    # -- connection management ---------------------------------------------
    def _conn(self) -> socket.socket:
        sock = getattr(self._tls, "sock", None)
        if sock is None:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._tls.sock = sock
        return sock

    def _drop_conn(self) -> None:
        sock = getattr(self._tls, "sock", None)
        self._tls.sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        self._drop_conn()

    # -- rpc core -----------------------------------------------------------
    def _rpc(self, op: str, key: str = "", *, payload: bytes = b"",
             rpc_timeout: Optional[float] = None, **fields) -> Tuple[
                 dict, bytes]:
        if self._closed:
            raise StoreUnavailable("store client is closed")
        deadline = time.monotonic() + self.fail_after
        delay = self.retry_base
        failures = 0
        req = dict(fields, op=op, key=key, nbytes=len(payload))
        while True:
            try:
                sock = self._conn()
                sock.settimeout(self.timeout if rpc_timeout is None
                                else rpc_timeout + self.timeout)
                _send_msg(sock, req, payload)
                resp, data = _recv_msg(sock)
                if failures:
                    obs.event("store_reconnect", host=self.host,
                              port=self.port, op=op, retries=failures)
                obs.counter("dl4j_store_rpc_total",
                            "Coordination-store operations by op and "
                            "backend", ("op", "backend")).inc(
                                op=op, backend=self.backend)
                if "error" in resp:
                    raise ValueError(f"netstore {op}: {resp['error']}")
                return resp, data
            except (ConnectionError, socket.timeout, OSError) as exc:
                self._drop_conn()
                failures += 1
                obs.counter("dl4j_store_rpc_retries_total",
                            "Coordination-store RPC retries after "
                            "connection errors").inc()
                if time.monotonic() + delay > deadline:
                    raise StoreUnavailable(
                        f"store {self.host}:{self.port} unreachable for "
                        f"{self.fail_after:.1f}s ({op} {key!r}): "
                        f"{exc}") from exc
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    # -- DLES framing (the FileStore contract, end to end) -------------------
    def _frame(self, data: bytes) -> bytes:
        return _HEADER.pack(_MAGIC, zlib.crc32(data) & 0xFFFFFFFF,
                            len(data)) + data

    def _unframe(self, key: str, raw: bytes) -> Optional[bytes]:
        if len(raw) < _HEADER.size:
            return self._corrupt(key, "short_header")
        magic, crc, length = _HEADER.unpack_from(raw)
        payload = raw[_HEADER.size:]
        if magic != _MAGIC or len(payload) != length:
            return self._corrupt(key, "frame_mismatch")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return self._corrupt(key, "crc_mismatch")
        return payload

    def _corrupt(self, key: str, why: str) -> None:
        obs.counter("dl4j_elastic_store_corrupt_total",
                    "FileStore records failing frame/CRC validation").inc()
        obs.event("elastic_store_corrupt", key=key, reason=why,
                  backend=self.backend)
        return None

    # -- the FileStore surface ----------------------------------------------
    def set(self, key: str, data: bytes, *,
            ttl: Optional[float] = None) -> None:
        self._rpc("set", key, payload=self._frame(data),
                  **({"ttl": float(ttl)} if ttl else {}))

    def set_exclusive(self, key: str, data: bytes) -> bool:
        resp, _ = self._rpc("setx", key, payload=self._frame(data))
        return bool(resp.get("ok"))

    def cas(self, key: str, data: bytes, version: int) -> Tuple[bool, int]:
        """Compare-and-swap on the key's write version (0 = must be absent).
        Returns ``(won, current_version)``."""
        resp, _ = self._rpc("cas", key, payload=self._frame(data),
                            ver=int(version))
        return bool(resp.get("ok")), int(resp.get("ver", 0))

    def version(self, key: str) -> int:
        resp, _ = self._rpc("ver", key)
        return int(resp.get("ver", 0))

    def get(self, key: str) -> Optional[bytes]:
        resp, raw = self._rpc("get", key)
        if not resp.get("exists"):
            return None
        return self._unframe(key, raw)

    def exists(self, key: str) -> bool:
        resp, _ = self._rpc("exists", key)
        return bool(resp.get("exists"))

    def delete(self, key: str) -> None:
        self._rpc("delete", key)

    def prune(self, prefix: str) -> None:
        self._rpc("prune", prefix)

    def list(self, prefix: str) -> List[str]:
        resp, _ = self._rpc("list", prefix)
        return [str(n) for n in resp.get("names", [])]

    # -- JSON convenience ---------------------------------------------------
    def set_json(self, key: str, value: dict) -> None:
        self.set(key, json.dumps(value, sort_keys=True).encode("utf-8"))

    def set_json_exclusive(self, key: str, value: dict) -> bool:
        return self.set_exclusive(
            key, json.dumps(value, sort_keys=True).encode("utf-8"))

    def get_json(self, key: str) -> Optional[dict]:
        raw = self.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return self._corrupt(key, "json_decode")

    # -- watch ---------------------------------------------------------------
    def watch(self, prefix: str, token=None, timeout: float = 1.0):
        """Block until something under ``prefix`` changes relative to
        ``token`` (or ``timeout`` elapses); returns the new opaque token.
        ``token=None`` returns the current state token without waiting."""
        t0 = time.monotonic()
        if token is None:
            resp, _ = self._rpc("ping")
            return int(resp.get("rev", 0))
        resp, _ = self._rpc("watch", prefix, since=int(token),
                            timeout=float(timeout), rpc_timeout=float(timeout))
        obs.histogram("dl4j_store_watch_wait_seconds",
                      "Time spent blocked in store watch calls").observe(
                          time.monotonic() - t0)
        return int(resp.get("rev", 0))


# ---------------------------------------------------------------------------
# backend selection
# ---------------------------------------------------------------------------


def open_store(spec, **net_kwargs):
    """``tcp://host:port`` -> :class:`NetStore`; ``file:/path`` or a bare
    path -> :class:`FileStore`. The one constructor every elastic entry
    point routes through, so the backend is purely a deployment choice."""
    s = os.fspath(spec)
    if s.startswith("tcp://"):
        return NetStore(s, **net_kwargs)
    if s.startswith("file:"):
        s = s[len("file:"):]
    return FileStore(s)


def store_from_env(default=None):
    """Backend from ``DL4J_TPU_STORE`` (falling back to ``default``)."""
    spec = os.environ.get("DL4J_TPU_STORE", default)
    if spec is None:
        raise ValueError("DL4J_TPU_STORE is not set and no default given")
    return open_store(spec)


# ---------------------------------------------------------------------------
# CLI: the server process (tools/elastic_smoke.sh, tests)
# ---------------------------------------------------------------------------


def _cmd_serve(args) -> int:
    server = NetStoreServer(args.host, args.port, data_dir=args.data)
    host, port = server.start()
    line = f"{host}:{port}"
    if args.announce:
        tmp = f"{args.announce}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, args.announce)
    print(f"netstore listening on {line}", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.parallel.netstore",
        description="TCP coordination-store server for elastic training")
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("serve", help="run the KV server")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=0,
                   help="0 picks a free port (see --announce)")
    s.add_argument("--data", default=None,
                   help="directory to mirror records into (restart safety)")
    s.add_argument("--announce", default=None,
                   help="file to atomically write host:port into once bound")
    s.set_defaults(fn=_cmd_serve)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
