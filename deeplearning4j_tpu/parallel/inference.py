"""Parallel inference with request batching.

Parity: parallelism/ParallelInference.java:32 (modes:52, output:110-136) and
inference/observers/BatchedInferenceObservable.java. The reference keeps N
model replicas on N devices with a batching queue; on TPU one sharded model
serves all chips, so the capability reduces to: (a) a thread-safe front that
coalesces small requests into padded batches (the BATCHED mode), (b) direct
pass-through (INPLACE/SEQUENTIAL modes).
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import time

import numpy as np

from .. import obs
from ..utils import bucketing


class _Pending:
    __slots__ = ("x", "event", "result", "deadline")

    def __init__(self, x, deadline: Optional[float] = None):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.deadline = deadline  # perf_counter scale, None = no deadline


class ParallelInference:
    """Batched inference front-end.

    ``mode``: "inplace" (call straight through) or "batched" (coalesce queued
    requests into one device call of at most ``max_batch_size`` examples; a
    single oversized request still dispatches whole).

    ``bucket``: pad each drained batch's row count up to the shared bucket
    ladder (see ``utils.bucketing``) before dispatch, so steady-state mixed
    request sizes hit at most one compiled executable per bucket instead of
    one per distinct coalesced size. Defaults to the DL4J_TPU_BUCKETING env
    switch. Padded rows are zeros (inference is row-independent) and are
    sliced off before results fan back out to requesters.

    ``warmup``: AOT-compile the model's inference executable for EVERY
    bucket a coalesced batch can hit (``nn.aot.warm_serving``) before the
    first request, so time-to-first-request never pays an XLA compile.
    Defaults to the DL4J_TPU_AOT env switch.
    """

    def __init__(self, model, mode: str = "batched", max_batch_size: int = 32,
                 queue_limit: int = 64, worker: bool = True,
                 bucket: Optional[bool] = None, warmup: Optional[bool] = None):
        self.model = model
        self.mode = mode
        self.max_batch_size = max_batch_size
        import os as _os

        if _os.environ.get("DL4J_TPU_TUNE"):
            # tuner winner applied before bucketing/warmup read their envs
            from deeplearning4j_tpu import tune as _tune

            _tune.maybe_apply(model, "serve")
        self.bucket = bucketing.bucketing_enabled() if bucket is None else bucket
        if warmup is None:
            from ..nn import aot

            warmup = aot.enabled()
        if warmup:
            from ..nn import aot

            aot.warm_serving(model, max_batch_size)
        self._queue: "queue.Queue[_Pending]" = queue.Queue(maxsize=queue_limit)
        self._carry: Optional[_Pending] = None  # request deferred by _drain
        self._stop = threading.Event()
        self._lifecycle_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        if mode == "batched" and worker:
            self._thread = threading.Thread(target=self._worker_loop, daemon=True)
            self._thread.start()

    # -- public ------------------------------------------------------------
    def output(self, x, deadline_ms: Optional[float] = None) -> np.ndarray:
        """``deadline_ms`` (relative to now): a batched request still queued
        when its deadline passes is SHED — it fails fast with
        :class:`~deeplearning4j_tpu.serve.scheduler.ShedError` instead of
        returning a late answer, and counts into ``dl4j_shed_total`` /
        the SLO burn window (serve-tier semantics; docs/SERVING.md)."""
        from ..serve.scheduler import ShedError

        t0 = time.perf_counter()
        try:
            out = self._output(x, deadline_ms=deadline_ms)
        except ShedError:
            raise  # already accounted via observe_shed, not a latency sample
        except Exception:
            obs.observe_request("pi.output", time.perf_counter() - t0,
                                status="error", error=True)
            raise
        obs.observe_request("pi.output", time.perf_counter() - t0)
        return out

    def _output(self, x, deadline_ms: Optional[float] = None) -> np.ndarray:
        x = np.asarray(x)
        if self.mode != "batched" or self._thread is None:
            if self._stop.is_set():
                raise RuntimeError("ParallelInference is shut down")
            return np.asarray(self.model.output(x))
        deadline = (None if deadline_ms is None
                    else time.perf_counter() + float(deadline_ms) / 1e3)
        p = _Pending(x, deadline=deadline)
        # enqueue under the shutdown lock so a request can't slip into the
        # queue after shutdown() drained it (check-then-put race)
        with self._lifecycle_lock:
            if self._stop.is_set():
                raise RuntimeError("ParallelInference is shut down")
            self._queue.put(p)
            if obs.enabled():
                obs.gauge("dl4j_inference_queue_depth",
                          "Requests waiting in the batching queue"
                          ).set(self._queue.qsize())
        p.event.wait()
        if isinstance(p.result, Exception):
            raise p.result
        return p.result

    def shutdown(self):
        with self._lifecycle_lock:
            self._stop.set()
        if self._thread is not None:
            self._queue.put(_Pending(None))  # wake the worker
            self._thread.join(timeout=5)
            with self._lifecycle_lock:
                # fail requests stranded in the queue (or carried by the
                # worker's coalescer) so waiters don't hang
                if self._carry is not None:
                    p, self._carry = self._carry, None
                    if p.x is not None:
                        p.result = RuntimeError("ParallelInference shut down")
                        p.event.set()
                while True:
                    try:
                        p = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if p.x is not None:
                        p.result = RuntimeError("ParallelInference shut down")
                        p.event.set()

    # -- worker ------------------------------------------------------------
    def _drain(self) -> List[_Pending]:
        """Assemble one device batch: coalesce queued requests until the
        EXAMPLE count reaches ``max_batch_size`` (an oversized single request
        still goes through whole). A request that would overflow the cap is
        carried to the next batch, so the coalesced size — and hence the set
        of shape buckets a serving process can ever compile — is bounded."""
        if self._carry is not None:
            batch, self._carry = [self._carry], None
        else:
            batch = [self._queue.get()]
        n = len(batch[0].x) if batch[0].x is not None else 0
        while n < self.max_batch_size:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            if p.x is not None and n + len(p.x) > self.max_batch_size:
                self._carry = p
                break
            batch.append(p)
            if p.x is not None:
                n += len(p.x)
        return self._shed_expired([p for p in batch if p.x is not None])

    def _shed_expired(self, batch: List[_Pending]) -> List[_Pending]:
        """Fail queued requests whose deadline already passed instead of
        spending device time on answers nobody is waiting for."""
        live = [p for p in batch if p.deadline is None
                or p.deadline >= time.perf_counter()]
        for p in batch:
            if p not in live:
                from ..serve.scheduler import ShedError

                obs.observe_shed("pi.output", reason="deadline")
                p.result = ShedError(
                    "deadline", "deadline expired in the batching queue")
                p.event.set()
        return live

    def _worker_loop(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            if obs.enabled():
                obs.gauge("dl4j_inference_in_flight",
                          "Coalesced requests currently on device"
                          ).set(len(batch))
            try:
                sizes = [len(p.x) for p in batch]
                xs = np.concatenate([p.x for p in batch], axis=0)
                total = len(xs)
                if self.bucket and total > 0:
                    target = bucketing.bucket_size(total)
                    bucketing.telemetry().record_hit("pi.batched", total, target)
                    if target > total:
                        xs = np.concatenate(
                            [xs, np.zeros((target - total,) + xs.shape[1:], xs.dtype)])
                out = np.asarray(self.model.output(xs))[:total]
                ofs = 0
                for p, n in zip(batch, sizes):
                    p.result = out[ofs : ofs + n]
                    ofs += n
                    p.event.set()
            except Exception as e:  # propagate to all waiters
                for p in batch:
                    p.result = e
                    p.event.set()
            finally:
                if obs.enabled():
                    obs.gauge("dl4j_inference_in_flight",
                              "Coalesced requests currently on device").set(0)
