"""Tensor parallelism: PartitionSpec rules + the sharded trainer.

Megatron-style sharding expressed as metadata, not code: each layer type
maps its param names to PartitionSpecs over the mesh's ``model`` axis
(column-parallel in-projections, row-parallel out-projections); XLA/GSPMD
inserts the psum/all-gathers over ICI during compilation. Expert weights
(MixtureOfExperts) shard their leading E axis over the same axis = expert
parallelism.

``ShardedTrainer`` composes every axis: params placed per TP rules, batch
sharded over ``data``, the time axis of sequence inputs over ``seq`` (ring
attention picks the axis up via parallel/context.py), all inside the ONE
jitted train step the single-chip path uses.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.context import use_mesh

# Dense/output weight matrices at or above this element count shard
# column-wise over the model axis; smaller matrices stay replicated — below
# roughly this size the inserted collective + partial-matmul launch overhead
# outweighs the memory/FLOP split on current ICI. Tunable per call via
# tp_param_shardings(dense_shard_min_elems=...).
TP_DENSE_SHARD_MIN_ELEMS = 1 << 16


def _spec_for(layer, pname: str, value, model_axis: str,
              dense_shard_min_elems: int = TP_DENSE_SHARD_MIN_ELEMS) -> P:
    """TP PartitionSpec for one param of one layer (replicated fallback)."""
    t = getattr(layer, "_type_name", "")
    if t == "multi_head_attention":
        return {
            "Wqkv": P(None, model_axis),  # column-parallel heads
            "bqkv": P(model_axis),
            "Wo": P(model_axis, None),    # row-parallel out-proj
            "bo": P(),
        }.get(pname, P())
    if t == "transformer_block":
        return {
            "Wi": P(None, model_axis),
            "bi": P(model_axis),
            "Wo": P(model_axis, None),
            "bo": P(),
        }.get(pname, P())
    if t == "mixture_of_experts":
        # expert parallelism: shard the expert axis
        if pname in ("Wi", "bi", "Wo", "bo"):
            return P(model_axis)
        return P()
    if t in ("dense", "output") and pname == "W" \
            and np.prod(value.shape) >= dense_shard_min_elems:
        return P(None, model_axis)  # shard big FF matrices column-wise
    if t in ("embedding", "embedding_sequence") and pname == "W":
        return P(None, model_axis)  # shard embedding features
    return P()


def tp_param_shardings(model, mesh: Mesh, model_axis: str = "model",
                       dense_shard_min_elems: int = TP_DENSE_SHARD_MIN_ELEMS):
    """Per-param NamedShardings for a MultiLayerNetwork's params pytree.

    Every sharded dimension is VALIDATED against the mesh axis size up
    front, so a bad config (e.g. MixtureOfExperts whose n_experts does not
    divide the model axis) fails with a named error instead of a cryptic
    GSPMD one at compile time."""

    def layer_specs(layer, params):
        def walk(sub, owner):
            out = {}
            for name, v in sub.items():
                if isinstance(v, dict):
                    # nested param subtree: the OWNING config declares which
                    # sub-layer the params belong to (nested_param_layers) —
                    # no name-based guessing
                    inner_owner = owner.nested_param_layers().get(name, owner)
                    out[name] = walk(v, inner_owner)
                else:
                    spec = _spec_for(owner, name, v, model_axis,
                                     dense_shard_min_elems)
                    # Hard-validate only the MoE expert axis: an uneven
                    # expert split silently changes routing capacity. Other
                    # uneven shardings are legal — GSPMD pads them under jit.
                    if getattr(owner, "_type_name", "") == "mixture_of_experts":
                        for dim, ax in enumerate(spec):
                            if ax is None:
                                continue
                            size, n = v.shape[dim], mesh.shape[ax]
                            if size % n:
                                raise ValueError(
                                    f"TP sharding: {type(owner).__name__}."
                                    f"{name} dim {dim} (size {size}) is not "
                                    f"divisible by mesh axis '{ax}' ({n}) — "
                                    "make n_experts a multiple of the "
                                    f"'{ax}' axis")
                    out[name] = NamedSharding(mesh, spec)
            return out

        return walk(params, layer)

    return tuple(layer_specs(l, p) for l, p in zip(model.layers, model.params))


class ShardedTrainer:
    """Drives a MultiLayerNetwork's jitted step over a dp×tp×sp mesh.

    - params: placed per TP/EP rules (tp_param_shardings)
    - batch axis 0: sharded over ``data``
    - time axis 1 (rank-3 inputs): sharded over ``seq`` when the mesh has one
    - ring attention engages automatically for layers configured with
      ``sequence_parallel=True`` (mesh published via parallel.context)
    """

    def __init__(self, model, mesh: Mesh, *, shard_time: bool = True):
        self.model = model
        self.mesh = mesh
        self.shard_time = shard_time and "seq" in mesh.shape and mesh.shape["seq"] > 1
        if model.params is None:
            model.init()
        self._place_params()

    def _place_params(self):
        m = self.model
        shardings = tp_param_shardings(m, self.mesh)
        m.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), m.params, shardings,
            is_leaf=lambda x: isinstance(x, jax.Array),
        )
        repl = NamedSharding(self.mesh, P())
        m.state = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), m.state)
        # opt state mirrors param shardings: each slot ("m"/"v"/…) is a
        # params-like tree, so moment tensors shard exactly like their params
        new_opt = []
        for opt_layer, shard_layer in zip(m.opt_state, shardings):
            if not isinstance(opt_layer, dict):  # stateless updater (sgd/noop)
                new_opt.append(jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, repl), opt_layer))
                continue
            placed = {}
            for slot, tree in opt_layer.items():
                try:
                    placed[slot] = jax.tree_util.tree_map(
                        lambda a, s: jax.device_put(a, s), tree, shard_layer
                    )
                except ValueError:  # structure mismatch (scalar/extra state)
                    placed[slot] = jax.tree_util.tree_map(
                        lambda a: jax.device_put(a, repl), tree
                    )
            new_opt.append(placed)
        m.opt_state = tuple(new_opt)
        # Cached step/output fns may have been traced WITHOUT the mesh
        # context (no ring attention) — force a retrace under the mesh.
        m._step_fn = m._tbptt_step_fn = m._output_fn = None

    def _shard_batch(self, arr, is_seq: bool):
        if arr is None:
            return None
        from deeplearning4j_tpu.nn.model import _cast_input

        arr = _cast_input(arr, self.model.dtype)
        axes = ["data"] + (["seq"] if (is_seq and arr.ndim >= 3 and self.shard_time) else [])
        spec = P(*axes, *([None] * (arr.ndim - len(axes))))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def fit_batch(self, x, y, fmask=None, lmask=None):
        """One sharded training step; returns the loss (device scalar)."""
        with use_mesh(self.mesh):
            return self.model._fit_batch(
                self._shard_batch(x, True),
                self._shard_batch(y, True),
                self._shard_batch(fmask, True),
                self._shard_batch(lmask, True),
            )

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None):
        from deeplearning4j_tpu.nn.model import _iter_batches

        model = self.model
        for _ in range(epochs):
            source = data() if callable(data) else data
            for xb, yb, fm, lm in _iter_batches(source, batch_size):
                score = self.fit_batch(xb, yb, fm, lm)
                if model.listeners:
                    score = float(score)
                    for l in model.listeners:
                        l.iteration_done(model, model.iteration, score, len(xb))
            model.epoch += 1
        return model

    def output(self, x):
        with use_mesh(self.mesh):
            return self.model.output(self._shard_batch(x, True))
