"""graftlint CLI.

Usage::

    python -m deeplearning4j_tpu.analysis.lint deeplearning4j_tpu
    python -m deeplearning4j_tpu.analysis.lint PKG --fix-baseline
    python -m deeplearning4j_tpu.analysis.lint PKG --no-baseline --json
    python -m deeplearning4j_tpu.analysis.lint PKG --rules host-sync,jit-purity
    python -m deeplearning4j_tpu.analysis.lint PKG --changed
    python -m deeplearning4j_tpu.analysis.lint PKG --sarif out.sarif

Baseline workflow: ``baseline.json`` (next to this module by default) maps
line-number-free fingerprints (``path::rule::func::normalized-line-text``)
to allowed occurrence counts. Findings beyond the baseline fail the run
(exit 1); fingerprints in the baseline that no longer occur are reported as
stale (informational). ``--fix-baseline`` rewrites the file to match the
current findings exactly — review the diff like any other code change.

``--changed`` scopes the verdict to files git reports as modified or
untracked (the fast pre-commit path: the whole index is still built — the
interprocedural rules need it — but only findings in changed files can fail
the run, and stale-fingerprint noise from unchanged files is suppressed).
``--sarif FILE`` additionally writes a SARIF 2.1.0 log (``-`` = stdout):
new findings as ``error``/``baselineState: new``, grandfathered ones as
``note``/``unchanged``.

Exit codes (the tools/lint.sh contract, asserted by tools/bench_smoke.sh):
0 clean (vs baseline), 1 new findings, 2 usage/parse/git error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from collections import Counter
from typing import Dict, List, Optional, Sequence, Set

from deeplearning4j_tpu.analysis import rules as rules_mod
from deeplearning4j_tpu.analysis.engine import Finding, Index

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    allowed = data.get("allowed", {})
    return {str(k): int(v) for k, v in allowed.items()}


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts = Counter(f.fingerprint for f in findings)
    data = {
        "version": BASELINE_VERSION,
        "comment": "graftlint frozen findings; regenerate with --fix-baseline "
                   "and review the diff",
        "allowed": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")


def changed_paths(root: str) -> Optional[Set[str]]:
    """Paths (relative to the lint root's parent, i.e. the same convention
    as ``Finding.path``) git reports as modified vs HEAD or untracked.
    None when git is unavailable / not a repository."""
    parent = os.path.dirname(os.path.abspath(root))
    out: Set[str] = set()
    # --relative / ls-files both yield paths relative to the -C directory,
    # matching the Finding.path convention
    for args in (["diff", "--name-only", "--relative", "HEAD", "--"],
                 ["ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(
                ["git", "-C", parent] + args,
                capture_output=True, text=True, timeout=30, check=True)
        except (OSError, subprocess.SubprocessError):
            return None
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def diff_baseline(findings: Sequence[Finding], allowed: Dict[str, int]):
    """Split findings into (new, grandfathered) and report stale fingerprints."""
    budget = dict(allowed)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = sorted(k for k, v in budget.items() if v > 0)
    return new, old, stale


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis.lint",
        description="graftlint: JAX trace-safety static analysis")
    ap.add_argument("target", help="package directory (or single .py file) to lint")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline json path (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding and fail "
                         "if there are any")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite the baseline to match current findings")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run "
                         f"(default: all of {','.join(rules_mod.ALL_RULES)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a json array instead of text")
    ap.add_argument("--changed", action="store_true",
                    help="only findings in files git reports as changed "
                         "(vs HEAD) or untracked can fail the run — the "
                         "fast pre-commit path")
    ap.add_argument("--sarif", default=None, metavar="FILE",
                    help="also write a SARIF 2.1.0 log to FILE ('-' for "
                         "stdout)")
    args = ap.parse_args(argv)

    if not os.path.exists(args.target):
        print(f"graftlint: no such target: {args.target}", file=sys.stderr)
        return 2

    selected = None
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in selected if r not in rules_mod.ALL_RULES]
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(rules_mod.ALL_RULES)})", file=sys.stderr)
            return 2

    index = Index(args.target)
    if index.errors:
        for f in index.errors:
            print(f.render(), file=sys.stderr)
        return 2

    findings = rules_mod.run(index, selected)

    scope: Optional[Set[str]] = None
    if args.changed:
        scope = changed_paths(args.target)
        if scope is None:
            print("graftlint: --changed requires git and a repository "
                  "above the target", file=sys.stderr)
            return 2
        findings = [f for f in findings if f.path in scope]

    if args.fix_baseline:
        if args.changed:
            print("graftlint: --fix-baseline cannot be combined with "
                  "--changed (it would drop every unchanged file's "
                  "baseline entry)", file=sys.stderr)
            return 2
        path = args.baseline or DEFAULT_BASELINE
        save_baseline(path, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) "
              f"({len({f.fingerprint for f in findings})} fingerprints) "
              f"to {path}")
        return 0

    if args.no_baseline:
        allowed: Dict[str, int] = {}
    else:
        path = args.baseline or DEFAULT_BASELINE
        try:
            allowed = load_baseline(path)
        except FileNotFoundError:
            allowed = {}
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            print(f"graftlint: bad baseline {path}: {e}", file=sys.stderr)
            return 2

    new, old, stale = diff_baseline(findings, allowed)
    if args.changed:
        # scoped runs see only a slice of the findings, so absent
        # fingerprints are expected, not actionable
        stale = []

    if args.sarif:
        from deeplearning4j_tpu.analysis.sarif import to_sarif
        doc = json.dumps(to_sarif(findings, new), indent=2)
        if args.sarif == "-":
            print(doc)
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                fh.write(doc + "\n")

    if args.as_json:
        print(json.dumps([
            {"rule": f.rule, "path": f.path, "line": f.line, "func": f.func,
             "message": f.message, "new": f in set(new)}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ], indent=2))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"graftlint: note: {len(stale)} stale baseline "
                  "fingerprint(s) no longer occur; run --fix-baseline to prune:")
            for k in stale:
                print(f"  {k}")

    if new:
        print(f"graftlint: {len(new)} new finding(s) "
              f"({len(old)} grandfathered by baseline)", file=sys.stderr)
        return 1
    print(f"graftlint: clean ({len(old)} grandfathered, "
          f"{len(stale)} stale baseline entries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
