"""graftlint dataflow: the interprocedural, field-sensitive layer.

The engine (:mod:`deeplearning4j_tpu.analysis.engine`) classifies whole
functions (traced / hot / device-source). The distributed-correctness rules
need to reason about *values*: which names hold a donating step program,
which buffers die at a dispatch, which strings name durable store paths.
This module adds that layer on top of the existing :class:`engine.Index` —
still pure AST, nothing here imports jax or executes target code.

Three facts are computed, each threaded across the intra-package call graph
and tracked field-sensitively (``self.<attr>`` / ``obj.<attr>`` keys, per
class of the defining module):

- **donating callables** (:attr:`Dataflow.local_donations`,
  :attr:`Dataflow.class_attr_donations`, :attr:`Dataflow.global_donations`,
  :attr:`Dataflow.factory_returns`): ``jax.jit(f, donate_argnums=...)``,
  ``StepProgram(...)`` (whose default donates the ``(params, opt, state)``
  carry), factories returning either, and the names/attributes they are
  bound to.
- **donating params** (:attr:`Dataflow.param_donations`): calling function
  ``g`` donates the buffer passed at position *k* because ``g``'s body
  dispatches it into a donating program — the interprocedural summary that
  lets ``use-after-donate`` see through helpers.
- **durable params** (:attr:`Dataflow.durable_params`): positions through
  which checkpoint/bundle/store-marker paths flow, so raw writes inside
  helpers are judged by what their callers pass.

Statement-level def-use runs per function via :func:`ordered_statements` +
:class:`ValueTracker` (kill on rebind, sanction on
``jax.block_until_ready``), deliberately optimistic about control flow:
a kill on any path counts — the baseline absorbs what that misses, and any
NEW finding fails CI (same contract as the rest of graftlint).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.engine import (
    FunctionInfo,
    Index,
    dotted_name,
    is_jit_call,
    own_nodes,
)

__all__ = [
    "DURABLE_PATH_MARKERS",
    "Dataflow",
    "DispatchSite",
    "Donation",
    "Key",
    "key_of",
    "literal_argnums",
    "ordered_statements",
    "render_key",
    "string_constants",
]

# A tracked value: a local name ("local", name) or a one-level attribute
# access ("attr", base, attr) — field sensitivity for self.params,
# model.opt_state, and friends.
Key = Tuple[str, ...]

# Path fragments that mark a string as naming a durable artifact: FileStore
# blobs, checkpoints/bundles, the tune DB, exported weights. Writes reaching
# these must go through the CRC-framed atomic helpers (docs/ROBUSTNESS.md).
DURABLE_PATH_MARKERS = (
    "checkpoint", "ckpt", "bundle", "manifest", "lease", "blob",
    "aotbundle", "tune_db", "tunedb", "snapshot", "params_", "weights_",
    ".npz",
)

# Modules whose functions are protocol-safe sinks for durable names: they
# frame/CRC payloads end-to-end themselves (the netstore client speaks the
# same DLES framing as FileStore), so a durable key flowing into them is the
# protocol being honored, not bypassed. Durable-param taint stops here.
PROTOCOL_SAFE_SINK_MODULES = ("netstore",)


def key_of(expr: ast.AST) -> Optional[Key]:
    """The tracking key of an expression, or None for anything more complex
    than ``name`` / ``base.attr`` (subscripts, calls, nested attributes)."""
    if isinstance(expr, ast.Name):
        return ("local", expr.id)
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return ("attr", expr.value.id, expr.attr)
    return None


def render_key(key: Key) -> str:
    return key[1] if key[0] == "local" else f"{key[1]}.{key[2]}"


def literal_argnums(expr: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums spec: int or tuple/list of ints; None if the
    spec is computed (we then refuse to guess rather than misreport)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        vals = []
        for e in expr.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            vals.append(e.value)
        return tuple(vals)
    return None


def string_constants(node: ast.AST) -> List[str]:
    """Every string literal in a subtree (f-string fragments included)."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


def ordered_statements(fi: FunctionInfo) -> List[ast.stmt]:
    """The function's own statements in source order (nested def/class
    bodies excluded, compound-statement children flattened in)."""
    return [n for n in own_nodes(fi.node) if isinstance(n, ast.stmt)]


@dataclass(frozen=True)
class Donation:
    """A callable that donates the buffers at ``positions`` of its call."""

    positions: Tuple[int, ...]
    desc: str       # human-readable construction site
    line: int       # construction line (in desc's module)

    def shifted(self, by: int) -> Optional["Donation"]:
        pos = tuple(p - by for p in self.positions if p - by >= 0)
        return Donation(pos, self.desc, self.line) if pos else None


@dataclass
class DispatchSite:
    """One donating call: ``call`` donates ``donated`` (position, key,
    arg-expression) under ``donation``."""

    stmt: ast.stmt
    call: ast.Call
    donation: Donation
    donated: List[Tuple[int, Optional[Key], ast.AST]]


# Simple statements whose subtree contains no nested statements — the only
# places dispatch calls are harvested, so compound statements (visited later
# through their flattened children) are never double-counted.
_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AnnAssign, ast.AugAssign,
                 ast.Return)

# .dispatch() and __call__ both run the donating executable (StepProgram
# contract); .warm()/.lower() take abstract values and donate nothing.
_DISPATCH_ATTRS = {"dispatch"}

_STEP_PROGRAM_DEFAULT = (0, 1, 2)   # StepProgram's donate_argnums default


def _positional_params(fi: FunctionInfo) -> List[str]:
    a = getattr(fi.node, "args", None)   # Module pseudo-functions have none
    if a is None:
        return []
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


class Dataflow:
    """Interprocedural value facts over an :class:`engine.Index`."""

    def __init__(self, index: Index):
        self.index = index
        # ("module.dotted", class) -> attr -> Donation
        self.class_attr_donations: Dict[Tuple[str, str], Dict[str, Donation]] = {}
        # ("module.dotted", name) -> Donation (module-level bindings)
        self.global_donations: Dict[Tuple[str, str], Donation] = {}
        # function qualname -> Donation of its return value (factories)
        self.factory_returns: Dict[str, Donation] = {}
        # function qualname -> {positional param index -> Donation}
        self.param_donations: Dict[str, Dict[int, Donation]] = {}
        # function qualname -> positional param indices carrying durable paths
        self.durable_params: Dict[str, Set[int]] = {}
        self._local_cache: Dict[str, Dict[Key, Donation]] = {}
        self._build_donations()
        self._build_param_donations()
        self._build_durable_params()

    # -- donating-callable discovery ----------------------------------------

    def donation_of_expr(self, fi: FunctionInfo,
                         expr: ast.AST) -> Optional[Donation]:
        """Does evaluating ``expr`` yield a donating callable?"""
        sm = fi.module
        if isinstance(expr, ast.Call):
            kw = {k.arg: k.value for k in expr.keywords if k.arg}
            if is_jit_call(expr, sm):
                if "donate_argnums" not in kw:
                    return None
                pos = literal_argnums(kw["donate_argnums"])
                if not pos:
                    return None
                return Donation(pos, f"jax.jit(donate_argnums={pos})",
                                expr.lineno)
            d = dotted_name(expr.func, sm)
            if d and (d == "StepProgram" or d.endswith(".StepProgram")):
                if "donate_argnums" in kw:
                    pos = literal_argnums(kw["donate_argnums"])
                    if not pos:
                        return None
                else:
                    pos = _STEP_PROGRAM_DEFAULT
                return Donation(tuple(pos),
                                f"StepProgram(donate_argnums={tuple(pos)})",
                                expr.lineno)
            # factory call: make_step() where make_step returns a donating
            # program
            for callee in self.index.resolve_call(fi, expr.func):
                don = self.factory_returns.get(callee)
                if don:
                    return don
            return None
        if isinstance(expr, ast.Name):
            return self.global_donations.get((sm.dotted, expr.id))
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base in ("self", "cls"):
                if fi.class_name:
                    hit = self.class_attr_donations.get(
                        (sm.dotted, fi.class_name), {}).get(expr.attr)
                    if hit:
                        return hit
                for (mod, _cls), attrs in self.class_attr_donations.items():
                    if mod == sm.dotted and expr.attr in attrs:
                        return attrs[expr.attr]
            return None
        return None

    def _build_donations(self):
        # fixpoint: constructions -> bindings (attrs/globals) -> factories ->
        # constructions through factory calls
        for _ in range(4):
            changed = False
            for q, fi in self.index.functions.items():
                sm = fi.module
                for node in own_nodes(fi.node):
                    if isinstance(node, ast.Return) and node.value is not None:
                        don = self.donation_of_expr(fi, node.value)
                        if don and self.factory_returns.get(q) != don:
                            self.factory_returns[q] = don
                            changed = True
                    elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                        value = node.value
                        if value is None:
                            continue
                        don = self.donation_of_expr(fi, value)
                        if not don:
                            continue
                        targets = (node.targets if isinstance(node, ast.Assign)
                                   else [node.target])
                        for t in targets:
                            k = key_of(t)
                            if k is None:
                                continue
                            if k[0] == "attr" and k[1] in ("self", "cls") \
                                    and fi.class_name:
                                table = self.class_attr_donations.setdefault(
                                    (sm.dotted, fi.class_name), {})
                                if table.get(k[2]) != don:
                                    table[k[2]] = don
                                    changed = True
                            elif k[0] == "local" and not fi.scope:
                                gk = (sm.dotted, k[1])
                                if self.global_donations.get(gk) != don:
                                    self.global_donations[gk] = don
                                    changed = True
            if not changed:
                break
        self._local_cache.clear()

    def local_donations(self, fi: FunctionInfo) -> Dict[Key, Donation]:
        """Names/attrs bound to donating callables within ``fi``'s body
        (flow-insensitive: one pre-pass, later dispatch lookups hit it)."""
        cached = self._local_cache.get(fi.qualname)
        if cached is not None:
            return cached
        env: Dict[Key, Donation] = {}
        for node in own_nodes(fi.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                    and node.value is not None:
                don = self.donation_of_expr(fi, node.value)
                if not don:
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    k = key_of(t)
                    if k:
                        env[k] = don
        self._local_cache[fi.qualname] = env
        return env

    # -- dispatch-site detection ---------------------------------------------

    def _callee_donation(self, fi: FunctionInfo,
                         call: ast.Call) -> Optional[Donation]:
        """Donation of a call through a donating value: ``prog(args)``,
        ``prog.dispatch(args)``, ``self._step.dispatch(args)``,
        ``jax.jit(f, donate_argnums=...)(args)``."""
        target = call.func
        if isinstance(target, ast.Attribute) and target.attr in _DISPATCH_ATTRS:
            target = target.value
        don = self.donation_of_expr(fi, target)
        if don:
            return don
        k = key_of(target)
        if k:
            don = self.local_donations(fi).get(k)
            if don:
                return don
        return None

    def _summary_donation(self, fi: FunctionInfo,
                          call: ast.Call) -> Optional[Donation]:
        """Donation through an interprocedural summary: calling ``g(x, y)``
        where ``g`` donates its param k means arg k dies here."""
        best: Optional[Donation] = None
        bound = (isinstance(call.func, ast.Attribute)
                 and isinstance(call.func.value, ast.Name)
                 and call.func.value.id in ("self", "cls"))
        for callee in self.index.resolve_call(fi, call.func):
            summary = self.param_donations.get(callee)
            if not summary:
                continue
            don = Donation(tuple(sorted(summary)),
                           f"call into {callee.split('::')[-1]} "
                           f"(donates params {tuple(sorted(summary))})",
                           call.lineno)
            if bound:
                don = don.shifted(1)   # self is param 0, not a call arg
            if don:
                best = don
                break
        return best

    def dispatch_sites(self, fi: FunctionInfo) -> List[DispatchSite]:
        """Every donating call in ``fi``, with the donated arg keys."""
        sites: List[DispatchSite] = []
        for stmt in ordered_statements(fi):
            if not isinstance(stmt, _SIMPLE_STMTS):
                continue
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                don = self._callee_donation(fi, node)
                if don is None:
                    don = self._summary_donation(fi, node)
                if don is None:
                    continue
                if any(isinstance(a, ast.Starred) for a in node.args):
                    continue   # *args dispatch: positions unknowable
                donated = []
                for pos in don.positions:
                    if pos < len(node.args):
                        arg = node.args[pos]
                        donated.append((pos, key_of(arg), arg))
                if donated:
                    sites.append(DispatchSite(stmt, node, don, donated))
        return sites

    # -- interprocedural summaries --------------------------------------------

    def _build_param_donations(self):
        """Fixpoint: a function donates its positional param k if its body
        passes that param (by name) at a donated position of a donating
        dispatch — including dispatches recognized through summaries found
        in earlier iterations."""
        for _ in range(4):
            changed = False
            for q, fi in self.index.functions.items():
                if isinstance(fi.node, ast.Module):
                    continue
                pos_params = _positional_params(fi)
                if not pos_params:
                    continue
                for site in self.dispatch_sites(fi):
                    for _pos, k, _arg in site.donated:
                        if not k or k[0] != "local" or k[1] not in pos_params:
                            continue
                        i = pos_params.index(k[1])
                        table = self.param_donations.setdefault(q, {})
                        if i not in table:
                            table[i] = site.donation
                            changed = True
            if not changed:
                break

    def _build_durable_params(self):
        """Fixpoint: param k of a callee is durable-tainted if any caller
        passes an expression carrying a durable path marker (literally or
        through its own durable names/params)."""
        for _ in range(4):
            changed = False
            for q, fi in self.index.functions.items():
                durable_names = self.durable_names(fi)
                for node in own_nodes(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callees = self.index.resolve_call(fi, node.func)
                    if not callees:
                        continue
                    bound = (isinstance(node.func, ast.Attribute)
                             and isinstance(node.func.value, ast.Name)
                             and node.func.value.id in ("self", "cls"))
                    for ai, arg in enumerate(node.args):
                        if isinstance(arg, ast.Starred):
                            continue
                        if not self.expr_durable(fi, arg, durable_names):
                            continue
                        for callee in callees:
                            mod = callee.split("::", 1)[0].rsplit(".", 1)[-1]
                            if mod in PROTOCOL_SAFE_SINK_MODULES:
                                continue
                            cfi = self.index.functions.get(callee)
                            if cfi is None or isinstance(cfi.node, ast.Module):
                                continue
                            pp = _positional_params(cfi)
                            pi = ai + (1 if bound else 0)
                            if pi >= len(pp):
                                continue
                            slots = self.durable_params.setdefault(callee, set())
                            if pi not in slots:
                                slots.add(pi)
                                changed = True
            if not changed:
                break

    # -- durable-path taint ----------------------------------------------------

    @staticmethod
    def _marks_durable(text: str) -> bool:
        low = text.lower()
        return any(m in low for m in DURABLE_PATH_MARKERS)

    def durable_params_of(self, fi: FunctionInfo) -> Set[str]:
        slots = self.durable_params.get(fi.qualname, set())
        pp = _positional_params(fi)
        return {pp[i] for i in slots if i < len(pp)}

    def durable_names(self, fi: FunctionInfo) -> Set[str]:
        """Local names through which a durable path flows: seeded by marker
        string literals and durable params, propagated through assignments
        (two passes reach a fixpoint for straight-line join chains)."""
        names: Set[str] = set(self.durable_params_of(fi))
        nodes = own_nodes(fi.node)

        def tainted(expr: ast.AST) -> bool:
            return self.expr_durable(fi, expr, names)

        for _ in range(2):
            before = len(names)
            for node in nodes:
                if isinstance(node, ast.Assign) and tainted(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                names.add(n.id)
                elif isinstance(node, ast.AnnAssign) and node.value is not None \
                        and tainted(node.value):
                    if isinstance(node.target, ast.Name):
                        names.add(node.target.id)
            if len(names) == before:
                break
        return names

    def expr_durable(self, fi: FunctionInfo, expr: ast.AST,
                     durable_names: Set[str]) -> bool:
        """Does ``expr`` plausibly evaluate to a durable path?"""
        for n in ast.walk(expr):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and self._marks_durable(n.value):
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in durable_names:
                return True
        return False

    def replace_sanctioned(self, fi: FunctionInfo) -> Set[str]:
        """Names that feed ``os.replace``/``os.rename``/``os.link`` as the
        SOURCE arg in this function — the tmp half of the
        write-tmp-then-rename (or tmp-then-link, for exclusive create)
        discipline. Writes targeting these are the sanctioned spelling,
        not a finding."""
        out: Set[str] = set()
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func, fi.module) in (
                        "os.replace", "os.rename", "os.link") and node.args:
                for n in ast.walk(node.args[0]):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        return out
