"""graftlint distributed-correctness rules (the dataflow-backed families).

| rule                   | hazard                                              |
|------------------------|-----------------------------------------------------|
| use-after-donate       | read of a buffer already donated into a step        |
| collective-consistency | rank-divergent / axis-mismatched collectives        |
| durable-store-protocol | raw writes to checkpoint/bundle/store paths         |

All three run on :class:`analysis.dataflow.Dataflow` — the interprocedural,
field-sensitive layer over the engine's call graph — so a donation through
``self._step`` built in ``__init__``, a helper that donates its parameter,
or a durable path handed down two calls all resolve. Inline
``# graftlint: disable=<rule>`` suppressions are honored via
``Index.make_finding`` like every other rule.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.dataflow import (
    Dataflow,
    Key,
    key_of,
    ordered_statements,
    render_key,
    string_constants,
)
from deeplearning4j_tpu.analysis.engine import (
    Finding,
    FunctionInfo,
    Index,
    dotted_name,
    own_nodes,
)

__all__ = [
    "DISTRIBUTED_RULES",
    "run_distributed",
]

DISTRIBUTED_RULES = (
    "use-after-donate",
    "collective-consistency",
    "durable-store-protocol",
)


def run_distributed(index: Index,
                    rules: Optional[Sequence[str]] = None) -> List[Finding]:
    active = set(rules) if rules else set(DISTRIBUTED_RULES)
    df = index.dataflow
    out: List[Finding] = []
    if "use-after-donate" in active:
        out += _rule_use_after_donate(index, df)
    if "collective-consistency" in active:
        out += _rule_collective_consistency(index)
    if "durable-store-protocol" in active:
        out += _rule_durable_store_protocol(index, df)
    return out


# ---------------------------------------------------------------------------
# statement-scan plumbing shared by the rules
# ---------------------------------------------------------------------------

# statements whose full subtree is scanned (no nested statements inside);
# compound statements contribute only their header expressions — their body
# statements are visited on their own through the flattened statement list
_SIMPLE = (ast.Expr, ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Return,
           ast.Raise, ast.Assert, ast.Delete)


def _scan_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a statement evaluates at its own source position."""
    if isinstance(stmt, _SIMPLE):
        return [stmt]
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    return []


def _kill_keys(stmt: ast.stmt) -> Set[Key]:
    """Keys (re)bound or deleted by a statement — optimistic kills."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    out: Set[Key] = set()

    def add(t: ast.AST):
        k = key_of(t)
        if k:
            out.add(k)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add(e)
        elif isinstance(t, ast.Starred):
            add(t.value)

    for t in targets:
        add(t)
    return out


def _keys_mentioned(node: ast.AST) -> Set[Key]:
    out: Set[Key] = set()
    for n in ast.walk(node):
        k = key_of(n)
        if k:
            out.add(k)
    return out


def _is_barrier_call(node: ast.AST, fi: FunctionInfo) -> bool:
    """``jax.block_until_ready(...)`` / ``<x>.block_until_ready()`` — the
    sanctioned host-side sync that pins a value before/around donation."""
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr == "block_until_ready":
        return True
    return dotted_name(node.func, fi.module) == "jax.block_until_ready"


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------


def _alias_base(value: ast.AST) -> Optional[Key]:
    """The key a plain alias expression reads from: ``y``, ``y.attr``,
    ``y[...]`` / ``y.attr[...]``. Donating the alias kills the base's
    buffer too — rebinding the alias does not resurrect it."""
    if isinstance(value, ast.Subscript):
        return key_of(value.value)
    return key_of(value)


def _rule_use_after_donate(index: Index, df: Dataflow) -> List[Finding]:
    """A value passed at a donated position of a step dispatch is dead: the
    executable owns (or aliased away) its buffer. Any later read on a path
    without a rebind or an explicit ``block_until_ready`` barrier is flagged
    — on TPU/GPU that read returns garbage or raises; on CPU, where XLA may
    ignore donation, it silently reads a stale buffer
    (``DL4J_TPU_DONATION_GUARD=1`` turns that into a loud failure). Aliases
    are tracked one level deep: donating ``x`` bound from ``base.attr[...]``
    kills ``base.attr`` as well."""
    out: List[Finding] = []
    for q in sorted(index.functions):
        fi = index.functions[q]
        sites = df.dispatch_sites(fi)
        if not sites:
            continue
        by_stmt: Dict[int, list] = {}
        for s in sites:
            by_stmt.setdefault(id(s.stmt), []).append(s)

        stmts = ordered_statements(fi)
        loops = [(n.lineno, getattr(n, "end_lineno", n.lineno) or n.lineno)
                 for n in own_nodes(fi.node)
                 if isinstance(n, (ast.For, ast.AsyncFor, ast.While))]
        dead: Dict[Key, tuple] = {}      # key -> (site, donate position)
        killed_at: Dict[Key, List[int]] = {}   # key -> kill/sanction lines
        flagged: Set[Tuple[Key, int]] = set()
        alias_of: Dict[Key, Key] = {}    # key -> base it aliases
        # alias bases dead at each site's dispatch, for the loop-carry pass
        site_alias: Dict[Tuple[int, int], Key] = {}

        for stmt in stmts:
            exprs = _scan_exprs(stmt)
            # 1) barrier sanction: block_until_ready naming a dead key
            #    re-legitimizes it (the PR 4 barrier placements)
            for e in exprs:
                for n in ast.walk(e):
                    if _is_barrier_call(n, fi):
                        for k in _keys_mentioned(n):
                            if dead.pop(k, None) is not None:
                                killed_at.setdefault(k, []).append(stmt.lineno)
            # 2) reads of dead keys
            for e in exprs:
                for n in ast.walk(e):
                    if not isinstance(n, (ast.Name, ast.Attribute)):
                        continue
                    if not isinstance(getattr(n, "ctx", None), ast.Load):
                        continue
                    k = key_of(n)
                    if k is None or k not in dead:
                        continue
                    site, pos = dead.pop(k)
                    if (k, site.call.lineno) in flagged:
                        continue
                    flagged.add((k, site.call.lineno))
                    f = index.make_finding(
                        "use-after-donate", fi, n.lineno,
                        f"'{render_key(k)}' was donated at line "
                        f"{site.call.lineno} (arg {pos} of "
                        f"{site.donation.desc}) and is dead here: rebind it "
                        "from the dispatch outputs or barrier with "
                        "jax.block_until_ready before reuse")
                    if f:
                        out.append(f)
            # 3) new dispatches, against the PRE-statement alias state (the
            #    RHS donates before the LHS rebinds). Donated keys rebound
            #    by this very statement stay live — `p, _ = step(p, x)` is
            #    the sanctioned idiom — but an aliased base dies regardless.
            for site in by_stmt.get(id(stmt), ()):
                own = _kill_keys(stmt)
                for pos, k, arg in site.donated:
                    base = _alias_base(arg) if k is None else alias_of.get(k)
                    if base is not None and base not in own \
                            and base not in dead:
                        dead[base] = (site, pos)
                        site_alias[(id(site), pos)] = base
                    if k is None or k in own:
                        continue
                    dead[k] = (site, pos)
            # 4) kills: rebinding / del ends tracking (and dissolves any
            #    alias relationship the old binding carried)
            for k in _kill_keys(stmt):
                if k in dead and dead[k][0].stmt is not stmt:
                    dead.pop(k)
                killed_at.setdefault(k, []).append(stmt.lineno)
                alias_of.pop(k, None)
            # 4b) alias bindings: `x = base.attr[...]` — donating x later
            #     kills base.attr's buffer no matter what x rebinds to
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tk = key_of(stmt.targets[0])
                bk = _alias_base(stmt.value)
                if tk and bk and bk != tk:
                    alias_of[tk] = bk

        # 5) loop carry: a donated key (or the base it aliases) never
        #    rebound before the loop's next iteration touches a dead buffer
        for site in sites:
            line = site.call.lineno
            enclosing = [(a, b) for a, b in loops if a <= line <= b]
            if not enclosing:
                continue
            _, loop_end = min(enclosing, key=lambda ab: ab[1] - ab[0])
            own = _kill_keys(site.stmt)
            for pos, k, _arg in site.donated:
                carried = []
                if k is not None and k not in own:
                    carried.append((k, False))
                base = site_alias.get((id(site), pos))
                if base is not None and base not in own:
                    carried.append((base, True))
                for ck, is_alias in carried:
                    if (ck, line) in flagged:
                        continue
                    if any(line < kl <= loop_end
                           for kl in killed_at.get(ck, ())):
                        continue
                    flagged.add((ck, line))
                    via = (f" (via its alias donated as arg {pos})"
                           if is_alias else f" (arg {pos})")
                    f = index.make_finding(
                        "use-after-donate", fi, line,
                        f"'{render_key(ck)}' is donated here{via} into "
                        f"{site.donation.desc} inside a loop but never "
                        "rebound before the next iteration can touch the "
                        "dead buffer; rebind it from the outputs "
                        "(`x, ... = step(x, ...)`) or copy before donating")
                    if f:
                        out.append(f)
    return out


# ---------------------------------------------------------------------------
# collective-consistency
# ---------------------------------------------------------------------------

# cross-replica primitives that must be issued identically by every member
# of the axis (arXiv 2004.13336's sharded update is bit-exact only then;
# mismatches are the gloo-preamble / gpipe-clip taxonomies of TEST_DEBT.md)
_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "pcast", "pvary",
}
_RANK_SOURCES_LEAF = {"axis_index", "process_index"}


def _collective_leaf(node: ast.Call, fi: FunctionInfo) -> Optional[str]:
    d = dotted_name(node.func, fi.module) or ""
    leaf = d.rsplit(".", 1)[-1]
    if leaf not in _COLLECTIVES:
        return None
    parts = d.split(".")
    if "lax" in parts or "jax" in parts or d == leaf:
        return leaf
    return None


def _is_rank_source(node: ast.AST, fi: FunctionInfo) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func, fi.module) or ""
    return d.rsplit(".", 1)[-1] in _RANK_SOURCES_LEAF


def _rank_tainted_names(fi: FunctionInfo) -> Set[str]:
    """Names carrying a member-identity value (axis_index/process_index),
    propagated through straight-line assignments."""
    tainted: Set[str] = set()

    def expr_tainted(e: ast.AST) -> bool:
        for n in ast.walk(e):
            if _is_rank_source(n, fi):
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in tainted:
                return True
        return False

    nodes = own_nodes(fi.node)
    for _ in range(2):
        before = len(tainted)
        for node in nodes:
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        if len(tainted) == before:
            break
    return tainted


def _collective_scope(index: Index) -> Tuple[Set[str], Dict[str, Set[str]]]:
    """(functions to check, axis-name environment per function).

    Scope: anything containing a collective or rank source, plus everything
    reachable from a ``shard_map`` body. The env maps body functions to the
    literal axis names visible at their shard_map call sites (in_specs /
    out_specs / axis kwargs), unioned over sites and propagated down the
    call graph."""
    scope: Set[str] = set()
    roots_env: Dict[str, Set[str]] = {}
    for q, fi in index.functions.items():
        has = False
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Call) and (
                    _collective_leaf(node, fi) or _is_rank_source(node, fi)):
                has = True
            if isinstance(node, ast.Call):
                d = dotted_name(node.func, fi.module) or ""
                if d.rsplit(".", 1)[-1] == "shard_map" and node.args:
                    axes: Set[str] = set()
                    for a in list(node.args[1:]) + [k.value for k in
                                                    node.keywords]:
                        axes.update(s for s in string_constants(a) if s)
                    for root in index._roots_from(fi, node.args[0], 0):
                        roots_env.setdefault(root, set()).update(axes)
        if has:
            scope.add(q)
    env: Dict[str, Set[str]] = {}
    for root, axes in roots_env.items():
        for q in index._reach({root}, index.edges):
            env.setdefault(q, set()).update(axes)
            scope.add(q)
    return scope, env


def _axis_literals(call: ast.Call) -> List[str]:
    """Literal axis names of a collective call (positional arg 1 or the
    axis_name/axis_index_groups-adjacent kwargs); [] when computed."""
    expr: Optional[ast.AST] = None
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            expr = kw.value
    if expr is None and len(call.args) > 1:
        expr = call.args[1]
    if expr is None:
        return []
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, (ast.Tuple, ast.List)):
        vals = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return []   # mixed/computed: refuse to guess
        return vals
    return []


def _branch_collective_seq(index: Index, fi: FunctionInfo,
                           expr: ast.AST) -> Optional[Tuple[str, ...]]:
    """Ordered collective ops a cond/switch branch issues; None when the
    branch cannot be resolved statically."""
    if isinstance(expr, ast.Lambda):
        return tuple(_collective_leaf(n, fi)
                     for n in ast.walk(expr.body)
                     if isinstance(n, ast.Call) and _collective_leaf(n, fi))
    if isinstance(expr, ast.Call):
        d = dotted_name(expr.func, fi.module) or ""
        if d.rsplit(".", 1)[-1] == "partial" and expr.args:
            return _branch_collective_seq(index, fi, expr.args[0])
        return None
    if isinstance(expr, (ast.Name, ast.Attribute)):
        hits = (index.resolve_call(fi, expr)
                if isinstance(expr, ast.Attribute)
                else ([index._resolve_local(fi, expr.id)]
                      if index._resolve_local(fi, expr.id) else []))
        if len(hits) != 1:
            return None
        cfi = index.functions.get(hits[0])
        if cfi is None:
            return None
        return tuple(_collective_leaf(n, cfi)
                     for n in own_nodes(cfi.node)
                     if isinstance(n, ast.Call) and _collective_leaf(n, cfi))
    return None


def _rule_collective_consistency(index: Index) -> List[Finding]:
    """Inside mesh/shard_map step bodies every member of an axis must issue
    the SAME collective sequence with the SAME axis names — a collective
    under rank-dependent control flow, a branch whose arms diverge, or an
    axis name outside the mesh's set deadlocks or miscompiles (the
    gloo-preamble rank disagreement and the gpipe-clip GSPMD taxonomies,
    docs/TEST_DEBT.md)."""
    out: List[Finding] = []
    scope, env = _collective_scope(index)
    for q in sorted(scope):
        fi = index.functions[q]
        tainted = _rank_tainted_names(fi)

        def test_ranky(e: ast.AST) -> bool:
            for n in ast.walk(e):
                if _is_rank_source(n, fi):
                    return True
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in tainted:
                    return True
            return False

        # (a) collectives lexically under rank-dependent control flow
        def scan(node: ast.AST, under_rank: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                u = under_rank
                if isinstance(child, (ast.If, ast.While, ast.IfExp)) \
                        and test_ranky(child.test):
                    u = True
                if under_rank and isinstance(child, ast.Call):
                    leaf = _collective_leaf(child, fi)
                    if leaf:
                        f = index.make_finding(
                            "collective-consistency", fi, child.lineno,
                            f"lax.{leaf} under rank-dependent control flow "
                            "(branch on axis_index/process_index): members "
                            "that skip it deadlock the axis or corrupt the "
                            "collective's matching (gloo-preamble class); "
                            "hoist the collective out of the branch")
                        if f:
                            out.append(f)
                scan(child, u)

        scan(fi.node, False)

        # (b) axis-name literal checks against the shard_map site env
        fenv = env.get(q, set())
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            leaf = _collective_leaf(node, fi)
            if leaf:
                lits = _axis_literals(node)
                dup = {a for a in lits if lits.count(a) > 1}
                if dup:
                    f = index.make_finding(
                        "collective-consistency", fi, node.lineno,
                        f"lax.{leaf} repeats axis name(s) "
                        f"{sorted(dup)} in one axis spec: reducing an axis "
                        "twice is at best redundant, at worst a "
                        "shadowed-axis bug")
                    if f:
                        out.append(f)
                if fenv:
                    missing = [a for a in lits if a not in fenv]
                    if missing:
                        f = index.make_finding(
                            "collective-consistency", fi, node.lineno,
                            f"lax.{leaf} names axis {missing} but the "
                            f"enclosing shard_map binds {sorted(fenv)}: "
                            "unbound or shadowed axis names fail at trace "
                            "time on some paths and silently no-op on "
                            "others")
                        if f:
                            out.append(f)

            # (c) rank-selected branch arms with divergent (or unverifiable)
            # collective sequences
            d = dotted_name(node.func, fi.module) or ""
            if d.rsplit(".", 1)[-1] in ("cond", "switch") \
                    and ("lax" in d.split(".")) and len(node.args) >= 2:
                branch_exprs: List[ast.AST] = []
                if isinstance(node.args[1], (ast.Tuple, ast.List)):
                    branch_exprs = list(node.args[1].elts)
                elif d.rsplit(".", 1)[-1] == "cond" and len(node.args) >= 3:
                    branch_exprs = [node.args[1], node.args[2]]
                else:
                    branch_exprs = [node.args[1]]
                seqs = [_branch_collective_seq(index, fi, b)
                        for b in branch_exprs]
                ranky = test_ranky(node.args[0])
                if all(s is not None for s in seqs) and len(set(seqs)) > 1:
                    f = index.make_finding(
                        "collective-consistency", fi, node.lineno,
                        f"lax.{d.rsplit('.', 1)[-1]} branch arms issue "
                        f"different collective sequences "
                        f"({[list(s) for s in seqs]}): all arms trace into "
                        "one program, so their collectives must match "
                        "exactly (gpipe-clip class)")
                    if f:
                        out.append(f)
                elif ranky and any(s is None for s in seqs):
                    f = index.make_finding(
                        "collective-consistency", fi, node.lineno,
                        f"rank-selected lax.{d.rsplit('.', 1)[-1]} whose "
                        "branches cannot be statically shown to issue "
                        "identical collective sequences; verify the arms "
                        "are collective-free (or normalized, e.g. pvary) "
                        "and suppress")
                    if f:
                        out.append(f)
    return out


# ---------------------------------------------------------------------------
# durable-store-protocol
# ---------------------------------------------------------------------------

_RAW_SAVERS = {"numpy.save", "numpy.savez", "numpy.savez_compressed"}
_WRITE_METHODS = {"write_text", "write_bytes"}


def _open_mode(call: ast.Call) -> str:
    expr: Optional[ast.AST] = None
    if len(call.args) > 1:
        expr = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            expr = kw.value
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return "r" if expr is None else ""


def _rule_durable_store_protocol(index: Index, df: Dataflow) -> List[Finding]:
    """Writes reaching FileStore blob / checkpoint / bundle / tune-DB paths
    must go through the CRC-framed atomic helpers (``_atomic_write_zip``,
    DLES framing, write-tmp-then-``os.replace``): a raw ``open(path, "w")``
    or ``np.save`` on a durable path tears under crash/preemption and the
    reader sees a half-written artifact (docs/ROBUSTNESS.md). Exclusive
    create must spell ``os.link`` (atomic on POSIX *and* NFS), not
    ``open(..., "x")``."""
    out: List[Finding] = []
    for q in sorted(index.functions):
        fi = index.functions[q]
        durable = df.durable_names(fi)
        sanctioned = df.replace_sanctioned(fi)

        def flagged_path(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Name) and n.id in sanctioned:
                    return False   # the tmp half of tmp -> os.replace
            return df.expr_durable(fi, expr, durable)

        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func, fi.module) or ""
            f = None
            if d in ("open", "io.open", "builtins.open") and node.args:
                mode = _open_mode(node)
                writes = any(c in mode for c in "wax+")
                if writes and flagged_path(node.args[0]):
                    if "x" in mode:
                        f = index.make_finding(
                            "durable-store-protocol", fi, node.lineno,
                            "exclusive-create open(..., 'x') on a durable "
                            "path: O_EXCL is not atomic on NFS and leaves a "
                            "partial file on crash; publish via write-tmp "
                            "then os.link (FileStore.set_exclusive)")
                    else:
                        f = index.make_finding(
                            "durable-store-protocol", fi, node.lineno,
                            f"raw open(..., {mode!r}) on a durable path: a "
                            "crash mid-write tears the artifact for every "
                            "reader; write a tmp file and os.replace it "
                            "(utils.serialization._atomic_write_zip / "
                            "FileStore framing)")
            elif d in _RAW_SAVERS and node.args \
                    and flagged_path(node.args[0]):
                f = index.make_finding(
                    "durable-store-protocol", fi, node.lineno,
                    f"np.{d.rsplit('.', 1)[-1]} straight onto a durable "
                    "path: the write is not atomic — save to a tmp path "
                    "and os.replace, or route through the checkpoint "
                    "helpers")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _WRITE_METHODS \
                    and flagged_path(node.func.value):
                f = index.make_finding(
                    "durable-store-protocol", fi, node.lineno,
                    f".{node.func.attr}() on a durable path: not atomic; "
                    "write tmp then os.replace")
            if f:
                out.append(f)
    return out
