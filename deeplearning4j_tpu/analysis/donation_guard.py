"""Runtime donation guard: donated host references must actually die.

The static side (``rules.py`` ``use-after-donate``) proves code doesn't
*obviously* read a buffer after donating it into a step executable; this
module proves the *process* didn't get away with one the analysis missed.
The gap exists because backends are forgiving: XLA:CPU may silently ignore
``donate_argnums`` (the input stays live and a use-after-donate reads the
stale-but-valid old buffer — the silent-wrong-answer flavor), while TPU/GPU
alias the buffer away (the same read returns garbage or raises). A test
suite that only runs on CPU therefore can't catch the bug class the
donation contract exists for.

Under ``DL4J_TPU_DONATION_GUARD=1``, :class:`StepProgram.__call__`
(``nn/step_program.py``) calls :func:`check_after_dispatch` after every
donating dispatch. The guard blocks on the outputs, then POISONS every
donated input leaf the backend left alive — ``jax.Array.delete()`` — so
any later host read raises ``RuntimeError: Array has been deleted`` loudly,
exactly where a real accelerator would have returned garbage. Each poisoned
leaf increments ``dl4j_donation_guard_trips_total`` and logs one obs event
per site.

Opt-in for the same reason the retrace guard is: poisoning is the point,
and it converts donation-contract leniency into hard failures — a debug
mode for tests and repros, never a default. Nothing here imports jax at
module import time.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, List, Sequence, Set, Tuple

from deeplearning4j_tpu import obs

__all__ = [
    "GuardTrip",
    "TRIPS_COUNTER",
    "check_after_dispatch",
    "enabled",
    "reset_warnings",
]

TRIPS_COUNTER = "dl4j_donation_guard_trips_total"

_trips = obs.counter(
    TRIPS_COUNTER,
    "donated-but-live input buffers poisoned by the donation guard")


def enabled() -> bool:
    return os.environ.get("DL4J_TPU_DONATION_GUARD", "0") != "0"


@dataclass(frozen=True)
class GuardTrip:
    """One donated input leaf the backend left alive (now poisoned)."""

    site: str
    position: int       # donate_argnums position of the offending argument
    shape: Tuple[int, ...]


# one obs event per site per process; tests reset between cases
_evented: Set[str] = set()
_evented_lock = threading.Lock()


def reset_warnings() -> None:
    with _evented_lock:
        _evented.clear()


def check_after_dispatch(site: str, args: Sequence[Any],
                         donate_argnums: Sequence[int],
                         outputs: Any) -> List[GuardTrip]:
    """Poison donated inputs that survived ``site``'s dispatch.

    Blocks on ``outputs`` first (an async in-flight execution may still be
    reading its inputs), then deletes every donated input leaf that is a
    live ``jax.Array``. On backends that honor donation the leaves are
    already deleted and this is a no-op sweep; on forgiving backends each
    deletion is a trip — counted, evented once per site, and guaranteed to
    turn any missed use-after-donate into an immediate RuntimeError."""
    if not enabled() or not donate_argnums:
        return []
    import jax

    jax.block_until_ready(outputs)
    trips: List[GuardTrip] = []
    for pos in donate_argnums:
        if pos >= len(args):
            continue
        for leaf in jax.tree_util.tree_leaves(args[pos]):
            if not isinstance(leaf, jax.Array):
                continue
            try:
                if leaf.is_deleted():
                    continue
                shape = tuple(leaf.shape)
                leaf.delete()
            except (RuntimeError, AttributeError):  # already invalidated
                continue
            trips.append(GuardTrip(site, pos, shape))
            _trips.inc()
    if trips:
        with _evented_lock:
            first = site not in _evented
            _evented.add(site)
        if first:
            obs.event("donation_guard", site=site, poisoned=len(trips),
                      positions=sorted({t.position for t in trips}))
    return trips
